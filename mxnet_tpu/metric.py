"""Evaluation metrics (reference: python/mxnet/metric.py — EvalMetric
registry: Accuracy, TopK, F1, MAE/MSE/RMSE, CrossEntropy, Perplexity,
CompositeEvalMetric, custom metrics; SURVEY.md 5.5)."""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

from .base import MXNetError, Registry

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MAE", "MSE",
           "RMSE", "CrossEntropy", "Perplexity", "Loss", "PearsonCorrelation",
           "CompositeEvalMetric", "CustomMetric", "create", "np_metric"]

_REG = Registry("metric")


def register(klass):
    _REG.register(klass.__name__.lower(), klass, override=True)
    return klass


def _to_numpy(x):
    from .ndarray import NDArray
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


from .util import as_list as _as_list


class EvalMetric:
    """Base metric with the reference's update/get/reset contract."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[n] for n in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[n] for n in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name, value = [name], [value]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        super().__init__(name, **kwargs)
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label)
            if pred.ndim > label.ndim:
                pred = np.argmax(pred, axis=self.axis)
            pred = pred.astype(np.int32).ravel()
            label = label.astype(np.int32).ravel()
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        super().__init__(f"{name}_{top_k}", **kwargs)
        self.top_k = top_k

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype(np.int32)
            topk = np.argsort(-pred, axis=-1)[..., :self.top_k]
            hit = (topk == label[..., None]).any(axis=-1)
            self.sum_metric += float(hit.sum())
            self.num_inst += hit.size


@register
class F1(EvalMetric):
    """Binary F1 (reference: metric.py F1; average='macro' over resets)."""

    def __init__(self, name="f1", average="macro", **kwargs):
        self.average = average
        super().__init__(name, **kwargs)

    def reset(self):
        self.tp = self.fp = self.fn = 0
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype(np.int32).ravel()
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = np.argmax(pred, axis=-1)
            else:
                pred = (pred.ravel() > 0.5).astype(np.int32)
            pred = pred.astype(np.int32).ravel()
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())
            self.num_inst += 1

    def get(self):
        prec = self.tp / max(self.tp + self.fp, 1)
        rec = self.tp / max(self.tp + self.fn, 1)
        f1 = 2 * prec * rec / max(prec + rec, 1e-12)
        return (self.name, f1)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_numpy(label), _to_numpy(pred)
            self.sum_metric += float(np.abs(label - pred.reshape(label.shape)).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_numpy(label), _to_numpy(pred)
            self.sum_metric += float(((label - pred.reshape(label.shape)) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.sqrt(self.sum_metric / self.num_inst))


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        super().__init__(name, **kwargs)
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).astype(np.int32).ravel()
            pred = _to_numpy(pred)
            prob = pred[np.arange(label.shape[0]), label]
            self.sum_metric += float(-np.log(prob + self.eps).sum())
            self.num_inst += label.shape[0]


@register
class Perplexity(CrossEntropy):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        super().__init__(name=name, **kwargs)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).astype(np.int32).ravel()
            pred = _to_numpy(pred).reshape(-1, _to_numpy(pred).shape[-1])
            prob = pred[np.arange(label.shape[0]), label]
            if self.ignore_label is not None:
                mask = label != self.ignore_label
                prob = prob[mask]
            self.sum_metric += float(-np.log(prob + self.eps).sum())
            self.num_inst += prob.shape[0]

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, math.exp(self.sum_metric / self.num_inst))


@register
class Loss(EvalMetric):
    """Mean of raw loss outputs (reference: metric.py Loss)."""

    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in _as_list(preds):
            pred = _to_numpy(pred)
            self.sum_metric += float(pred.sum())
            self.num_inst += pred.size


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label, pred = _to_numpy(label).ravel(), _to_numpy(pred).ravel()
            if label.std() > 0 and pred.std() > 0:
                self.sum_metric += float(np.corrcoef(label, pred)[0, 1])
            self.num_inst += 1


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        super().__init__(name, **kwargs)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.extend(_as_list(n))
            values.extend(_as_list(v))
        return (names, values)


class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False,
                 **kwargs):
        super().__init__(f"custom({name})", **kwargs)
        self._feval = feval

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            val = self._feval(_to_numpy(label), _to_numpy(pred))
            if isinstance(val, tuple):
                s, n = val
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += val
                self.num_inst += 1


def np_metric(numpy_feval, name=None, allow_extra_outputs=False):
    """Decorator creating a CustomMetric from a numpy function
    (reference: mx.metric.np)."""
    def factory():
        return CustomMetric(numpy_feval, name or numpy_feval.__name__,
                            allow_extra_outputs)
    return factory


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if callable(metric):
        return CustomMetric(metric, **kwargs)
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m))
        return composite
    if isinstance(metric, str):
        klass = _REG.find(metric.lower().replace("-", ""))
        if klass is None:
            aliases = {"acc": Accuracy, "ce": CrossEntropy,
                       "top_k_accuracy": TopKAccuracy, "top_k_acc": TopKAccuracy}
            klass = aliases.get(metric.lower())
        if klass is None:
            raise MXNetError(f"unknown metric {metric!r}")
        return klass(*args, **kwargs)
    raise MXNetError(f"cannot create metric from {metric!r}")
