"""Profiler: chrome-trace host spans + XLA device traces.

Reference surface: ``python/mxnet/profiler.py`` over ``src/profiler/``
(``MXSetProcessProfilerConfig``/``MXDumpProfile`` — SURVEY.md 5.1): a
``set_config``/``start``/``stop`` lifecycle that writes a chrome://tracing
JSON file with per-op and user-scoped events, plus aggregate summaries.

TPU-native redesign: host spans (op dispatch, user scopes, steps) are
recorded by the imperative dispatcher itself; *device* time lives in XLA,
so ``set_config(device_trace=...)`` tees ``jax.profiler`` into a TensorBoard
trace directory alongside the chrome JSON — the TPU equivalent of the
reference's GPU kernel timeline.  Dispatch spans are wall-clock on the
host; XLA execution is async, so a span measures dispatch+trace cost, not
device occupancy (that is what the device trace is for).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from .base import MXNetError

__all__ = ["set_config", "set_state", "start", "stop", "pause", "resume",
           "dump", "dumps", "scope", "Task", "Frame", "Event", "Counter",
           "Marker"]

_lock = threading.Lock()
_state = {
    "running": False,
    "paused": False,
    "filename": "profile.json",
    "profile_imperative": True,
    "profile_symbolic": True,
    "profile_memory": False,
    "profile_api": True,
    "aggregate_stats": False,
    "device_trace": None,       # logdir for jax.profiler, or None
    "events": [],               # chrome trace events
    "t0": None,
    "_jax_tracing": False,
}

# fast-path flag read by the dispatcher on every op call
_ACTIVE = False


def _now_us():
    return time.perf_counter() * 1e6


def set_config(**kwargs):
    """Configure (reference: profiler.set_config).  Accepted keys:
    filename, profile_all, profile_imperative, profile_symbolic,
    profile_memory, profile_api, aggregate_stats, device_trace (logdir
    for the XLA/TensorBoard device trace)."""
    if _state["running"]:
        raise MXNetError("set_config while profiler is running")
    allowed = {"filename", "profile_all", "profile_imperative",
               "profile_symbolic", "profile_memory", "profile_api",
               "aggregate_stats", "device_trace", "continuous_dump"}
    for k, v in kwargs.items():
        if k not in allowed:
            raise MXNetError(f"set_config: unknown option {k!r}")
        if k == "profile_all" and v:
            _state.update(profile_imperative=True, profile_symbolic=True,
                          profile_api=True, profile_memory=True)
        elif k != "profile_all":
            _state[k] = v


def set_state(state: str):
    """'run' | 'stop' (reference: profiler.set_state)."""
    if state == "run":
        start()
    elif state == "stop":
        stop()
    else:
        raise MXNetError(f"invalid profiler state {state!r}")


def start():
    global _ACTIVE
    with _lock:
        if _state["running"]:
            return
        _state["running"] = True
        _state["paused"] = False
        _state["t0"] = _now_us()
        _state["events"] = []
        _ACTIVE = True
        if _state["device_trace"]:
            try:
                import jax
                jax.profiler.start_trace(_state["device_trace"])
                _state["_jax_tracing"] = True
            except Exception:   # tracing backend unavailable: host-only
                _state["_jax_tracing"] = False


def stop():
    global _ACTIVE
    with _lock:
        if not _state["running"]:
            return
        _state["running"] = False
        _ACTIVE = False
        if _state["_jax_tracing"]:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            _state["_jax_tracing"] = False


def pause():
    global _ACTIVE
    _state["paused"] = True
    _ACTIVE = False


def resume():
    global _ACTIVE
    _state["paused"] = False
    _ACTIVE = _state["running"]


def _record(name: str, cat: str, t_start_us: float, dur_us: float,
            args: Optional[dict] = None):
    ev = {"name": name, "cat": cat, "ph": "X",
          "ts": t_start_us - _state["t0"], "dur": dur_us,
          "pid": os.getpid(), "tid": threading.get_ident()}
    if args:
        ev["args"] = args
    _state["events"].append(ev)


def record_op(opname: str, t_start_us: float, t_end_us: float):
    """Called by the imperative dispatcher (ops/registry.invoke)."""
    if not _ACTIVE or not _state["profile_imperative"]:
        return
    _record(opname, "operator", t_start_us, t_end_us - t_start_us)


class scope:
    """``with profiler.scope("step"):`` — a named host span (reference:
    profiler scope/Task API)."""

    def __init__(self, name: str, cat: str = "user"):
        self._name = name
        self._cat = cat
        self._t0 = None

    def __enter__(self):
        self._t0 = _now_us()
        return self

    def __exit__(self, *exc):
        if not _ACTIVE:
            return
        if self._cat == "symbolic" and not _state["profile_symbolic"]:
            return
        _record(self._name, self._cat, self._t0, _now_us() - self._t0)


class _Domain:
    def __init__(self, name="default"):
        self.name = name


class Task(scope):
    def __init__(self, domain=None, name="task"):
        super().__init__(name, "task")

    start = scope.__enter__

    def stop(self):
        self.__exit__()


Frame = Task
Event = Task


class Counter:
    """Named counter events (reference: profiler.Counter)."""

    def __init__(self, domain=None, name="counter", value=0):
        self.name = name
        self._value = value

    def set_value(self, value):
        self._value = value
        if _ACTIVE:
            _state["events"].append({
                "name": self.name, "ph": "C",
                "ts": _now_us() - _state["t0"], "pid": os.getpid(),
                "args": {self.name: self._value}})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)


class Marker:
    """Instant event (reference: profiler.Marker)."""

    def __init__(self, domain=None, name="marker"):
        self.name = name

    def mark(self, scope_kind="process"):
        if _ACTIVE:
            _state["events"].append({
                "name": self.name, "ph": "i",
                "ts": _now_us() - _state["t0"], "pid": os.getpid(),
                "tid": threading.get_ident(),
                "s": {"process": "p", "thread": "t",
                      "global": "g"}.get(scope_kind, "p")})


def dumps(reset=False, format="json") -> str:
    """Serialized profile.  format='json': chrome trace; 'table': the
    reference's aggregate-stats text summary."""
    with _lock:
        events = list(_state["events"])
        if reset:
            _state["events"] = []
    if format == "json":
        return json.dumps({"traceEvents": events,
                           "displayTimeUnit": "ms"}, indent=1)
    if format != "table":
        raise MXNetError(f"unknown dump format {format!r}")
    agg: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("ph") == "X":
            agg.setdefault(ev["name"], []).append(ev["dur"])
    lines = [f"{'Name':<40}{'Calls':>8}{'Total(us)':>14}{'Avg(us)':>12}"
             f"{'Max(us)':>12}"]
    for name, durs in sorted(agg.items(),
                             key=lambda kv: -sum(kv[1])):
        lines.append(f"{name:<40}{len(durs):>8}{sum(durs):>14.1f}"
                     f"{sum(durs) / len(durs):>12.1f}{max(durs):>12.1f}")
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):
    """Write the chrome-trace file (reference: profiler.dump)."""
    path = _state["filename"]
    with open(path, "w") as f:
        f.write(dumps())
    if _state["aggregate_stats"]:
        with open(path + ".summary.txt", "w") as f:
            f.write(dumps(format="table"))
    return path
