"""Profiler: chrome-trace host spans + XLA device traces.

Reference surface: ``python/mxnet/profiler.py`` over ``src/profiler/``
(``MXSetProcessProfilerConfig``/``MXDumpProfile`` — SURVEY.md 5.1): a
``set_config``/``start``/``stop`` lifecycle that writes a chrome://tracing
JSON file with per-op and user-scoped events, plus aggregate summaries.

TPU-native redesign: host spans (op dispatch, user scopes, steps) are
recorded by the imperative dispatcher itself; *device* time lives in XLA,
so ``set_config(device_trace=...)`` tees ``jax.profiler`` into a TensorBoard
trace directory alongside the chrome JSON — the TPU equivalent of the
reference's GPU kernel timeline.  Dispatch spans are wall-clock on the
host; XLA execution is async, so a span measures dispatch+trace cost, not
device occupancy (that is what the device trace is for).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from .base import MXNetError

__all__ = ["set_config", "set_state", "start", "stop", "pause", "resume",
           "dump", "dumps", "scope", "Task", "Frame", "Event", "Counter",
           "Marker", "sample_memory"]

# RLock: memory sampling and the event-append helper run inside
# start/stop critical sections
_lock = threading.RLock()
_state = {
    "running": False,
    "paused": False,
    "filename": "profile.json",
    "profile_imperative": True,
    "profile_symbolic": True,
    "profile_memory": False,
    "profile_api": True,
    "aggregate_stats": False,
    "device_trace": None,       # logdir for jax.profiler, or None
    "events": [],               # chrome trace events
    "continuous_dump": False,
    "t0": None,
    "_jax_tracing": False,
}

# fast-path flag read by the dispatcher on every op call
_ACTIVE = False
# re-entrancy guard: dump(finished=True) stops the profiler, and stop()
# auto-dumps under continuous_dump — without the guard they'd recurse
_DUMPING = False


def _now_us():
    return time.perf_counter() * 1e6


def _append_event(ev: dict):
    """Lock-protected event append: `dumps(reset=True)` swaps the event
    list under `_lock`, so writers must serialize against it or an event
    recorded mid-swap lands on the list being thrown away."""
    with _lock:
        _state["events"].append(ev)


# keys that may be re-configured while the profiler is running: the
# output path and the dump-on-stop policy affect only where/when events
# are written, never what is recorded
_RECONFIG_WHILE_RUNNING = {"filename", "continuous_dump"}


def set_config(**kwargs):
    """Configure (reference: profiler.set_config).  Accepted keys:
    filename, profile_all, profile_imperative, profile_symbolic,
    profile_memory, profile_api, aggregate_stats, continuous_dump
    (auto-dump on stop; dump() while running snapshots without reset),
    device_trace (logdir for the XLA/TensorBoard device trace).

    While the profiler is running only ``filename`` and
    ``continuous_dump`` may be changed (so the dump target can be picked
    after ``start()``); any other key raises."""
    allowed = {"filename", "profile_all", "profile_imperative",
               "profile_symbolic", "profile_memory", "profile_api",
               "aggregate_stats", "device_trace", "continuous_dump"}
    for k in kwargs:
        if k not in allowed:
            raise MXNetError(f"set_config: unknown option {k!r}")
    if _state["running"]:
        bad = set(kwargs) - _RECONFIG_WHILE_RUNNING
        if bad:
            raise MXNetError(
                f"set_config while profiler is running: only "
                f"{sorted(_RECONFIG_WHILE_RUNNING)} may change mid-run "
                f"(got {sorted(bad)})")
    with _lock:
        for k, v in kwargs.items():
            if k == "profile_all" and v:
                _state.update(profile_imperative=True,
                              profile_symbolic=True,
                              profile_api=True, profile_memory=True)
            elif k != "profile_all":
                _state[k] = v


def set_state(state: str):
    """'run' | 'stop' (reference: profiler.set_state)."""
    if state == "run":
        start()
    elif state == "stop":
        stop()
    else:
        raise MXNetError(f"invalid profiler state {state!r}")


def start():
    global _ACTIVE
    with _lock:
        if _state["running"]:
            return
        _state["running"] = True
        _state["paused"] = False
        _state["t0"] = _now_us()
        _state["events"] = []
        _ACTIVE = True
        if _state["device_trace"]:
            try:
                import jax
                jax.profiler.start_trace(_state["device_trace"])
                _state["_jax_tracing"] = True
            except Exception:   # tracing backend unavailable: host-only
                _state["_jax_tracing"] = False
    if _state["profile_memory"]:
        sample_memory()         # baseline live-bytes sample at t=0


def stop():
    global _ACTIVE
    with _lock:
        if not _state["running"]:
            return
        if _state["profile_memory"]:
            sample_memory()     # closing live-bytes sample while active
        _state["running"] = False
        _ACTIVE = False
        if _state["_jax_tracing"]:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            _state["_jax_tracing"] = False
    if _state["continuous_dump"] and not _DUMPING:
        dump()


def pause():
    global _ACTIVE
    with _lock:
        _state["paused"] = True
        _ACTIVE = False


def resume():
    global _ACTIVE
    with _lock:
        _state["paused"] = False
        _ACTIVE = _state["running"]


def _record(name: str, cat: str, t_start_us: float, dur_us: float,
            args: Optional[dict] = None):
    ev = {"name": name, "cat": cat, "ph": "X",
          "ts": t_start_us - _state["t0"], "dur": dur_us,
          "pid": os.getpid(), "tid": threading.get_ident()}
    if args:
        ev["args"] = args
    _append_event(ev)


def record_op(opname: str, t_start_us: float, t_end_us: float):
    """Called by the imperative dispatcher (ops/registry.invoke)."""
    if not _ACTIVE or not _state["profile_imperative"]:
        return
    _record(opname, "operator", t_start_us, t_end_us - t_start_us)


class scope:
    """``with profiler.scope("step"):`` — a named host span (reference:
    profiler scope/Task API)."""

    def __init__(self, name: str, cat: str = "user"):
        self._name = name
        self._cat = cat
        self._t0 = None

    def __enter__(self):
        self._t0 = _now_us()
        return self

    def __exit__(self, *exc):
        if not _ACTIVE:
            return
        if self._cat == "symbolic" and not _state["profile_symbolic"]:
            return
        _record(self._name, self._cat, self._t0, _now_us() - self._t0)


class _Domain:
    def __init__(self, name="default"):
        self.name = name


class Task(scope):
    def __init__(self, domain=None, name="task"):
        super().__init__(name, "task")

    start = scope.__enter__

    def stop(self):
        self.__exit__()


Frame = Task
Event = Task


class Counter:
    """Named counter events (reference: profiler.Counter)."""

    def __init__(self, domain=None, name="counter", value=0):
        self.name = name
        self._value = value

    def set_value(self, value):
        self._value = value
        if _ACTIVE:
            _append_event({
                "name": self.name, "ph": "C",
                "ts": _now_us() - _state["t0"], "pid": os.getpid(),
                "args": {self.name: self._value}})

    def increment(self, delta=1):
        self.set_value(self._value + delta)

    def decrement(self, delta=1):
        self.set_value(self._value - delta)


class Marker:
    """Instant event (reference: profiler.Marker)."""

    def __init__(self, domain=None, name="marker"):
        self.name = name

    def mark(self, scope_kind="process"):
        if _ACTIVE:
            _append_event({
                "name": self.name, "ph": "i",
                "ts": _now_us() - _state["t0"], "pid": os.getpid(),
                "tid": threading.get_ident(),
                "s": {"process": "p", "thread": "t",
                      "global": "g"}.get(scope_kind, "p")})


def sample_memory():
    """Sample per-device live bytes (``jax.Device.memory_stats()``, host
    RSS fallback) into the runtime-metrics ``memory.live_bytes`` gauge,
    and — when the profiler is running with ``profile_memory=True`` —
    emit a chrome-trace ``ph:"C"`` counter event so memory shares the
    trace timeline.  Returns the sampled ``(device, bytes, limit)``
    list."""
    from . import runtime_metrics as _rm
    stats = _rm.sample_memory()
    if _ACTIVE and _state["profile_memory"]:
        _append_event({
            "name": "memory.live_bytes", "ph": "C",
            "ts": _now_us() - _state["t0"], "pid": os.getpid(),
            "args": {dev: used for dev, used, _limit in stats}})
    return stats


def dumps(reset=False, format="json") -> str:
    """Serialized profile.  format='json': chrome trace; 'table': the
    reference's aggregate-stats text summary.

    When the runtime metrics registry is enabled, the JSON trace also
    carries one ``ph:"C"`` counter event per registry metric (snapshot
    at dump time), so op counters/histograms line up with host spans."""
    with _lock:
        events = list(_state["events"])
        if reset:
            _state["events"] = []
        t0 = _state["t0"]
    if format == "json":
        from . import runtime_metrics as _rm
        if _rm._ENABLED:
            events = events + _rm.chrome_counter_events(t0 or 0.0)
        return json.dumps({"traceEvents": events,
                           "displayTimeUnit": "ms"}, indent=1)
    if format != "table":
        raise MXNetError(f"unknown dump format {format!r}")
    agg: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("ph") == "X":
            agg.setdefault(ev["name"], []).append(ev["dur"])
    lines = [f"{'Name':<40}{'Calls':>8}{'Total(us)':>14}{'Avg(us)':>12}"
             f"{'Max(us)':>12}"]
    for name, durs in sorted(agg.items(),
                             key=lambda kv: -sum(kv[1])):
        lines.append(f"{name:<40}{len(durs):>8}{sum(durs):>14.1f}"
                     f"{sum(durs) / len(durs):>12.1f}{max(durs):>12.1f}")
    return "\n".join(lines)


def dump(finished=True, profile_process="worker"):
    """Write the chrome-trace file (reference: profiler.dump).

    ``finished=True`` while the profiler is running stops it first
    (reference semantics: the profile won't be resumed).  Under
    ``continuous_dump`` a mid-run ``dump(finished=False)`` snapshots the
    events so far without resetting them.  The target path is read at
    call time, so a ``set_config(filename=...)`` issued after
    ``start()`` is honored, and the path written is the path returned."""
    global _DUMPING
    if _state["running"] and finished:
        _DUMPING = True
        try:
            stop()
        finally:
            _DUMPING = False
    with _lock:
        path = _state["filename"]
        aggregate = _state["aggregate_stats"]
    with open(path, "w") as f:
        f.write(dumps())
    if aggregate:
        with open(path + ".summary.txt", "w") as f:
            f.write(dumps(format="table"))
    return path
