"""Persistent AOT compiled-executable cache (docs/serving.md §5).

Every server start and every bench round used to retrace and recompile
every shape bucket from scratch — minutes of dead time at production
replica counts and a p99 cliff on every hot-swap.  The "Automatic Full
Compilation … to Cloud TPUs" line (PAPERS.md) is the ahead-of-time
grounding: compile once, serialize the executable, reuse it everywhere
the (program, shape bucket, dtypes, device topology, jax version) key
matches.

Two tiers share this module:

- **Serving executables** (:class:`CompileCache`): content-addressed
  blobs of ``jax.experimental.serialize_executable`` payloads under
  ``MXNET_COMPILE_CACHE_DIR``.  Writes are atomic (tmp + rename), loads
  are corruption-tolerant (a bad blob is a miss that falls back to a
  fresh compile — never an error), and the directory is LRU-bounded by
  ``MXNET_COMPILE_CACHE_MAX_BYTES`` (eviction by least-recent use;
  hits refresh recency).  Consumers: ``deploy.StableHLOModel.
  aot_program`` / ``serving.ModelRepository`` bucket programs.
- **Training-side jit programs**: :func:`enable_jax_persistent_cache`
  routes jax's OWN persistent compilation cache into a shared
  directory and counts its hit/miss monitoring events — the bench
  harness (``bench.py``) uses it so successive rounds stop paying the
  full compile bill (BENCH r03/r05 hit the harness timeout largely on
  recompilation).

Payload format: ``b"MXAOT1" + sha256(body) + body`` where ``body`` is
the pickled ``(blob, in_tree, out_tree)`` triple from
``serialize_executable.serialize`` — the checksum is what makes a
truncated or bit-flipped entry a detectable miss instead of an opaque
deserialization crash.
"""
from __future__ import annotations

import hashlib
import logging
import os
import pickle
import tempfile
import time

from . import engine, faults as _faults, runtime_metrics as _rm
from .base import MXNetError, get_env

__all__ = ["CompileCache", "cache_key", "topology_fingerprint",
           "aot_program", "get_default", "enable_jax_persistent_cache"]

_LOG = logging.getLogger("mxnet_tpu")

_MAGIC = b"MXAOT1"
_DIGEST_BYTES = 32          # sha256
_SUFFIX = ".bin"


# --------------------------------------------------------------------- keys
def topology_fingerprint():
    """Device-topology + runtime-version component of every cache key: a
    serialized executable only reloads onto the platform/device-kind/
    count/process layout and jax/jaxlib pair it was compiled for."""
    try:
        import jax
        import jaxlib
        devs = jax.devices()
        kinds = ",".join(sorted({f"{d.platform}:{d.device_kind}"
                                 for d in devs}))
        return (f"{kinds}|n={len(devs)}|procs={jax.process_count()}"
                f"|jax={jax.__version__}|jaxlib={jaxlib.__version__}")
    except Exception:       # noqa: BLE001 — keyable even without a backend
        return "no-backend"


def cache_key(program_hash, bucket_rows, dtypes, topology=None):
    """Content address of one compiled executable:
    (program identity, shape bucket, input dtypes, device topology +
    jax/PJRT version) -> hex digest.  ``program_hash`` is the sha256 of
    the serialized StableHLO module (or any stable program fingerprint).
    """
    if topology is None:
        topology = topology_fingerprint()
    parts = "\x1f".join([str(program_hash), f"rows={bucket_rows}",
                         ",".join(str(d) for d in dtypes), topology])
    return hashlib.sha256(parts.encode()).hexdigest()


# ----------------------------------------------------------------- payloads
def _wrap_payload(body: bytes) -> bytes:
    return _MAGIC + hashlib.sha256(body).digest() + body


def _unwrap_payload(raw: bytes):
    """Checksum-verified body, or None for a corrupt/foreign blob."""
    if len(raw) < len(_MAGIC) + _DIGEST_BYTES \
            or not raw.startswith(_MAGIC):
        return None
    digest = raw[len(_MAGIC):len(_MAGIC) + _DIGEST_BYTES]
    body = raw[len(_MAGIC) + _DIGEST_BYTES:]
    if hashlib.sha256(body).digest() != digest:
        return None
    return body


def _serialize_compiled(compiled) -> bytes:
    """Compiled jax executable -> self-contained payload body."""
    from jax.experimental.serialize_executable import serialize
    return pickle.dumps(serialize(compiled))


def _deserialize_compiled(body: bytes):
    """Payload body -> loaded executable callable."""
    from jax.experimental.serialize_executable import deserialize_and_load
    blob, in_tree, out_tree = pickle.loads(body)
    return deserialize_and_load(blob, in_tree, out_tree)


def load_payload_file(path):
    """Read + checksum-verify one cache/shipped payload file.  Returns
    the body bytes, or None when missing/corrupt (never raises on bad
    data — a broken blob must degrade to a fresh compile)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return None
    return _unwrap_payload(raw)


def load_executable_file(path):
    """Payload file -> loaded executable callable (flagged with
    ``_mx_from_disk_cache=True``), or None on missing/corrupt/
    undeserializable content.  The no-cache-dir path for executables
    shipped inside an artifact (``export_stablehlo(precompile=...)``);
    observes the deserialize histogram like a cache hit."""
    body = load_payload_file(path)
    if body is None:
        return None
    t0 = time.perf_counter()
    try:
        loaded = _deserialize_compiled(body)
    except Exception:   # noqa: BLE001 — stale blob degrades to compile
        return None
    if _rm._ENABLED:
        _rm.COMPILE_CACHE_DESERIALIZE_SECONDS.observe(
            time.perf_counter() - t0)

    def prog(*xs):
        return loaded(*xs)
    prog._mx_from_disk_cache = True
    return prog


def write_payload_file(path, body):
    """Atomically write one payload file (tmp in the same dir +
    ``os.replace``), so a concurrent reader never sees a half-written
    blob and a crash never leaves a truncated entry under the real name.
    """
    d = os.path.dirname(path) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(_wrap_payload(body))
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# -------------------------------------------------------------------- cache
class CompileCache:
    """Content-addressed on-disk store of serialized executables.

    ``cache_dir=None`` (and ``MXNET_COMPILE_CACHE_DIR`` unset) disables
    the cache: every lookup misses cheaply and nothing touches disk.
    All byte-level operations are corruption-tolerant; counters
    (``hits``/``misses``/``corrupt``/``stores``/``evictions``) are
    always on (plain ints) and mirrored into ``runtime_metrics`` as
    ``compile.cache{event=...}`` when the registry is enabled.
    """

    def __init__(self, cache_dir=None, max_bytes=None):
        if cache_dir is None:
            cache_dir = get_env("MXNET_COMPILE_CACHE_DIR", typ=str)
        if max_bytes is None:
            max_bytes = get_env("MXNET_COMPILE_CACHE_MAX_BYTES", typ=int)
        self.cache_dir = cache_dir
        self._requested_dir = cache_dir     # identity even when the dir
        self.max_bytes = int(max_bytes) if max_bytes else 0  # is unusable
        self._lock = engine.make_lock("compile_cache.CompileCache._lock")
        self.hits = 0
        self.misses = 0
        self.corrupt = 0
        self.stores = 0
        self.evictions = 0
        if self.cache_dir:
            # an uncreatable dir (permission-denied parent, read-only
            # fs) degrades to cache-off with a warning — never an error
            # on the serving path, and diagnose must stay runnable to
            # report exactly this misconfiguration
            try:
                os.makedirs(self.cache_dir, exist_ok=True)
            except OSError as e:
                _LOG.warning("compile cache: cannot create %s (%s); "
                             "cache disabled", self.cache_dir, e)
                self.cache_dir = None
            else:
                self._sweep_orphan_tmp()

    def _sweep_orphan_tmp(self):
        """Unlink ``*.tmp`` litter left by writers killed between
        mkstemp and the atomic rename (the kill-and-restart lifecycle
        is this cache's whole point).  Age-gated to one minute so a
        concurrent replica's in-flight write is never yanked — real
        writes complete in milliseconds."""
        try:
            names = os.listdir(self.cache_dir)
        except OSError:
            return
        cutoff = time.time() - 60
        for name in names:
            if not name.endswith(".tmp"):
                continue
            path = os.path.join(self.cache_dir, name)
            try:
                if os.stat(path).st_mtime < cutoff:
                    os.unlink(path)
            except OSError:
                continue

    @property
    def enabled(self):
        return bool(self.cache_dir)

    def _path(self, key):
        return os.path.join(self.cache_dir, key + _SUFFIX)

    def _count(self, event):
        # callers hold no lock; counter writes take the instance lock so
        # concurrent workers don't lose increments
        with self._lock:
            setattr(self, _EVENT_ATTR[event],
                    getattr(self, _EVENT_ATTR[event]) + 1)
        if _rm._ENABLED:
            _rm.COMPILE_CACHE.inc(event=event)

    # ------------------------------------------------------------- bytes
    def contains(self, key):
        """Whether an entry exists on disk (no counters, no read)."""
        return self.enabled and os.path.exists(self._path(key))

    def _read_verified(self, key):
        """Checksum-verified body or None.  Counts ``corrupt`` (and
        unlinks the rot) but NOT hit/miss — callers count those once
        they know whether the payload was actually usable."""
        if not self.enabled:
            return None
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        try:
            # chaos site: blob rot (corrupt flips a byte -> the
            # checksum below turns it into a counted miss) or a slow/
            # failing cache volume — ALL modes degrade to a miss, the
            # cache's never-raise contract
            raw = _faults.inject("compile_cache.load", value=raw)
        except MXNetError:
            return None
        body = _unwrap_payload(raw)
        if body is None:
            self._discard_corrupt(path)
            return None
        try:
            os.utime(path, None)        # LRU recency
        except OSError:
            pass
        return body

    def get(self, key):
        """Checksum-verified payload body for ``key`` or None.  A hit
        refreshes the entry's recency (LRU); a corrupt blob is unlinked
        and counted both ``corrupt`` and ``miss`` — the miss counter's
        contract is "lookups that did NOT yield a usable payload", so
        it stays equal to the compiles that follow."""
        body = self._read_verified(key)
        self._count("hit" if body is not None else "miss")
        return body

    def put(self, key, body):
        """Atomically persist ``body`` under ``key`` and enforce the LRU
        size bound.  Best-effort: an unwritable cache dir logs once and
        degrades to cache-off behavior instead of failing the compile
        that produced the executable."""
        if not self.enabled:
            return False
        try:
            write_payload_file(self._path(key), body)
        except OSError as e:
            _LOG.warning("compile cache: cannot write %s: %s",
                         self.cache_dir, e)
            return False
        self._count("store")
        self._enforce_limit()
        return True

    def ingest(self, key, path):
        """Seed the cache from a shipped payload file (an
        ``export_stablehlo(precompile=...)`` artifact).  Returns True
        when the entry is (now) present and valid.  An existing entry
        is checksum-verified, not trusted: a bit-flipped cache blob
        must not shadow a pristine shipped one."""
        if not self.enabled:
            return False
        if self.contains(key) \
                and load_payload_file(self._path(key)) is not None:
            return True
        body = load_payload_file(path)
        if body is None:
            return False
        return self.put(key, body)

    def _discard_corrupt(self, path):
        try:
            os.unlink(path)
        except OSError:
            pass
        self._count("corrupt")

    def _entries(self):
        out = []
        try:
            names = os.listdir(self.cache_dir)
        except OSError:
            return out
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            path = os.path.join(self.cache_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append((path, st.st_mtime, st.st_size))
        return out

    def _enforce_limit(self):
        """Evict least-recently-used entries until the directory fits
        ``max_bytes`` (0 = unbounded).  The newest entry always stays,
        so one oversized executable degrades to a single-entry cache
        instead of evicting itself forever."""
        if not self.enabled or self.max_bytes <= 0:
            return
        entries = sorted(self._entries(), key=lambda e: e[1])
        total = sum(size for _p, _m, size in entries)
        while total > self.max_bytes and len(entries) > 1:
            path, _mtime, size = entries.pop(0)     # oldest first
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            self._count("evict")

    # ------------------------------------------------------- executables
    def load_executable(self, key):
        """Deserialize + load the executable stored under ``key`` onto
        the current devices.  Returns a callable flagged with
        ``_mx_from_disk_cache=True`` (the serving batcher reads the flag
        to label disk hits), or None on miss/corruption.

        Counting happens HERE, after deserialization: a blob that reads
        and checksums fine but no longer loads (stale PJRT plugin under
        an unchanged jax version) is a ``corrupt`` + ``miss``, never a
        hit — so ``misses`` stays equal to the XLA compiles that
        actually happen, which is what the CI round-trip asserts."""
        body = self._read_verified(key)
        if body is None:
            self._count("miss")
            return None
        t0 = time.perf_counter()
        try:
            loaded = _deserialize_compiled(body)
        except Exception:   # noqa: BLE001 — stale PJRT blob
            self._discard_corrupt(self._path(key))
            self._count("miss")
            return None
        self._count("hit")
        if _rm._ENABLED:
            _rm.COMPILE_CACHE_DESERIALIZE_SECONDS.observe(
                time.perf_counter() - t0)

        def prog(*xs):
            return loaded(*xs)
        prog._mx_from_disk_cache = True
        return prog

    def store_executable(self, key, compiled):
        """Serialize a freshly compiled executable under ``key``.
        Returns False (cache stays consistent, compile result unharmed)
        when the backend does not support executable serialization."""
        try:
            body = _serialize_compiled(compiled)
        except Exception as e:  # noqa: BLE001 — backend w/o serialization
            _LOG.debug("compile cache: executable not serializable: %s", e)
            return False
        return self.put(key, body)

    # ------------------------------------------------------------- stats
    def stats(self):
        """Plain-dict snapshot for diagnose/bench JSON: dir, entry
        count, total bytes, and this process's counters."""
        entries = self._entries() if self.enabled else []
        with self._lock:
            out = {"enabled": self.enabled, "dir": self.cache_dir,
                   "max_bytes": self.max_bytes,
                   "entries": len(entries),
                   "bytes": sum(s for _p, _m, s in entries),
                   "hits": self.hits, "misses": self.misses,
                   "corrupt": self.corrupt, "stores": self.stores,
                   "evictions": self.evictions}
        return out


_EVENT_ATTR = {"hit": "hits", "miss": "misses", "corrupt": "corrupt",
               "store": "stores", "evict": "evictions"}

# process-default instance, rebuilt whenever the env knobs change (so a
# test monkeypatching MXNET_COMPILE_CACHE_DIR gets a fresh cache without
# reaching into module state)
_DEFAULT = None
_DEFAULT_LOCK = engine.make_lock("compile_cache._DEFAULT_LOCK")


def get_default():
    """The env-configured process-wide cache (``MXNET_COMPILE_CACHE_DIR``
    / ``MXNET_COMPILE_CACHE_MAX_BYTES``); disabled when the dir is
    unset."""
    global _DEFAULT
    cache_dir = get_env("MXNET_COMPILE_CACHE_DIR", typ=str)
    max_bytes = get_env("MXNET_COMPILE_CACHE_MAX_BYTES", typ=int)
    with _DEFAULT_LOCK:
        if _DEFAULT is None or _DEFAULT._requested_dir != cache_dir \
                or _DEFAULT.max_bytes != (max_bytes or 0):
            _DEFAULT = CompileCache(cache_dir, max_bytes)
        return _DEFAULT


# ------------------------------------------------------------- AOT compile
def aot_program(fn, avals, key, cache=None, shipped_path=None):
    """Cache-through ahead-of-time compile: returns ``(prog, source)``
    where ``source`` is ``"disk"`` (deserialized from the cache or from
    ``shipped_path`` — zero XLA compiles) or ``"compile"`` (lowered +
    compiled now, and stored for the next process).  ``prog`` takes raw
    arrays matching ``avals`` exactly (the serving batcher pads every
    batch to its bucket, so the shapes always match).  ``shipped_path``
    is the last resort before compiling — it covers a disabled or
    unwritable cache AND a corrupt cache entry shadowing a pristine
    shipped executable."""
    import jax

    cache = get_default() if cache is None else cache
    if cache.enabled:
        prog = cache.load_executable(key)
        if prog is not None:
            return prog, "disk"
    if shipped_path is not None:
        prog = load_executable_file(shipped_path)
        if prog is not None:
            return prog, "disk"
    try:
        compiled = jax.jit(fn).lower(*avals).compile()
    except Exception as e:
        raise MXNetError(f"aot_program: compile failed for key "
                         f"{key[:12]}…: {e}") from e
    if cache.enabled:
        cache.store_executable(key, compiled)

    def prog(*xs):
        return compiled(*xs)
    prog._mx_from_disk_cache = False
    return prog, "compile"


# ----------------------------------------------- training-side (jax) cache
def enable_jax_persistent_cache(cache_dir):
    """Route jax's OWN persistent compilation cache (the training-side
    ``jax.jit`` path — distinct from the serving executable store
    above) into ``cache_dir``, with the size/time admission thresholds
    zeroed so every program persists.  Returns a live ``{"hits": n,
    "misses": n}`` dict updated from jax's compilation-cache monitoring
    events — the bench harness reports it per phase."""
    import jax
    from jax import monitoring

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    stats = {"hits": 0, "misses": 0}

    def _listener(event, **_kw):
        # the counts double as runtime metrics when the registry is on
        if event == "/jax/compilation_cache/cache_hits":
            stats["hits"] += 1
            if _rm._ENABLED:
                _rm.COMPILE_CACHE.inc(event="jax_hit")
        elif event == "/jax/compilation_cache/cache_misses":
            stats["misses"] += 1
            if _rm._ENABLED:
                _rm.COMPILE_CACHE.inc(event="jax_miss")

    monitoring.register_event_listener(_listener)
    return stats
