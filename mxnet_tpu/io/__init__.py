"""Data I/O subsystem (reference: python/mxnet/io/ + src/io/;
SURVEY.md §2.1 Data iterators row, §3.5)."""
from .io import (DataDesc, DataBatch, DataIter, ResizeIter,
                 PrefetchingIter, NDArrayIter, CSVIter, MNISTIter,
                 ImageRecordIter)

__all__ = ["DataDesc", "DataBatch", "DataIter", "ResizeIter",
           "PrefetchingIter", "NDArrayIter", "CSVIter", "MNISTIter",
           "ImageRecordIter"]
