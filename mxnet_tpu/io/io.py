"""Data iterators (reference: python/mxnet/io/io.py + src/io/).

TPU-native notes: the reference's C++ decode/augment threads
(``iter_image_recordio_2.cc``, ``PrefetcherIter``) are replaced by a
host-side NumPy/cv2 pipeline behind a background prefetch thread; batch
assembly is one contiguous NumPy array → one host→device transfer.  Device
work (normalization etc.) belongs in the compiled step, where XLA fuses it.

Sharding for the distributed tier uses the reference's ``num_parts`` /
``part_index`` contract: each worker iterates only its shard.
"""
from __future__ import annotations

import gzip
import os
import queue as _queue
import struct
import threading
import time
from collections import namedtuple

import numpy as np

from ..base import MXNetError
from .. import engine as _engine
from .. import faults as _faults
from .. import ndarray as nd
from ..ndarray import NDArray
from .. import perf_account as _pa
from .. import recordio
from .. import runtime_metrics as _rm
from .. import tracing as _tr

__all__ = ["DataDesc", "DataBatch", "DataIter", "ResizeIter",
           "PrefetchingIter", "NDArrayIter", "CSVIter", "MNISTIter",
           "ImageRecordIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape", "dtype", "layout"])):
    """Shape/type descriptor (reference: io.DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        return super().__new__(cls, name, tuple(shape), dtype, layout)

    @staticmethod
    def get_batch_axis(layout):
        return 0 if layout is None else layout.find("N")


class DataBatch:
    """One mini-batch (reference: io.DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            raise MXNetError("DataBatch.data must be a list of NDArrays")
        if label is not None and not isinstance(label, (list, tuple)):
            raise MXNetError("DataBatch.label must be a list of NDArrays")
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        shapes = [d.shape for d in self.data] if self.data else []
        lshapes = [l.shape for l in self.label] if self.label else []
        return f"DataBatch: data shapes: {shapes} label shapes: {lshapes}"


class DataIter:
    """Iterator base (reference: io.DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self) -> DataBatch:
        _faults.inject("train.data.next")
        # data-wait attribution: the interval this consumer spent in
        # next() becomes the following step's train.data.wait span
        # (perf_account.note_data_wait) — only when observing
        timed = _rm._ENABLED or _tr._ENABLED
        t0 = time.perf_counter() if timed else 0.0
        if self.iter_next():
            if _rm._ENABLED:
                _rm.IO_BATCHES.inc()
            batch = DataBatch(data=self.getdata(), label=self.getlabel(),
                              pad=self.getpad(), index=self.getindex())
            if timed:
                _pa.note_data_wait(t0, time.perf_counter())
            return batch
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        return 0


class ResizeIter(DataIter):
    """Truncate/loop an iterator to a fixed number of batches per epoch
    (reference: io.ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        for attr in ("provide_data", "provide_label", "default_bucket_key"):
            if hasattr(data_iter, attr):
                setattr(self, attr, getattr(data_iter, attr))

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iterators
    (reference: io.PrefetchingIter ≙ src/io PrefetcherIter)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        if len(iters) != 1 and (rename_data is None
                                or rename_label is None):
            raise MXNetError("multiple iters require rename_data/label")
        self.iters = iters
        # rename_*: one {old_name: new_name} dict per inner iter
        self._rename_data = rename_data
        self._rename_label = rename_label
        super().__init__(iters[0].batch_size)
        self._depth = prefetch_depth
        self._queue = None
        self._thread = None
        self._done = False
        self._start()

    def _renamed(self, attr, renames):
        descs = []
        for i, it in enumerate(self.iters):
            mapping = renames[i] if renames else {}
            for d in getattr(it, attr, []):
                descs.append(d._replace(name=mapping.get(d.name, d.name)))
        return descs

    @property
    def provide_data(self):
        return self._renamed("provide_data", self._rename_data)

    @property
    def provide_label(self):
        return self._renamed("provide_label", self._rename_label)

    def _start(self):
        self._queue = _queue.Queue(maxsize=self._depth)
        self._stop_evt = threading.Event()

        def worker():
            try:
                while not self._stop_evt.is_set():
                    try:
                        batches = [it.next() for it in self.iters]
                    except StopIteration:
                        self._queue.put(None)
                        return
                    self._queue.put(batches)
            except Exception as e:  # propagate to consumer
                self._queue.put(e)

        self._thread = _engine.make_thread(
            worker, name="mxnet-prefetch", owner="PrefetchingIter")
        self._thread.start()

    def reset(self):
        self._stop_evt.set()
        # drain so the worker can observe the stop event
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        self._thread.join(timeout=5)
        for it in self.iters:
            it.reset()
        self._done = False
        self._start()

    def next(self):
        _faults.inject("train.data.next")
        # the consumer-visible wait is just the queue take — the
        # producer thread's own timing never reaches a step (the
        # data-wait channel is thread-local by design)
        timed = _rm._ENABLED or _tr._ENABLED
        t0 = time.perf_counter() if timed else 0.0
        if self._done:
            raise StopIteration
        got = self._queue.get()
        if _rm._ENABLED:
            # depth AFTER this take: how far ahead the producer is
            _rm.IO_PREFETCH_DEPTH.set(self._queue.qsize())
        if got is None:
            self._done = True  # producer exited; don't block on next call
            raise StopIteration
        if isinstance(got, Exception):
            self._done = True
            raise got
        if len(self.iters) == 1:
            batch = got[0]
        else:
            batch = DataBatch(
                data=[d for b in got for d in b.data],
                label=[l for b in got for l in (b.label or [])],
                pad=got[0].pad)
        if timed:
            _pa.note_data_wait(t0, time.perf_counter())
        return batch

    def iter_next(self):
        raise MXNetError("PrefetchingIter supports next() only")


def _init_data(data, allow_empty, default_name):
    """-> list of (name, ndarray) (reference: io._init_data)."""
    if data is None:
        if not allow_empty:
            raise MXNetError("data cannot be None")
        return []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, (list, tuple)):
        pairs = []
        for i, d in enumerate(data):
            name = default_name if len(data) == 1 \
                else f"_{i}_{default_name}"
            pairs.append((name, d))
    elif isinstance(data, dict):
        pairs = list(data.items())
    else:
        raise MXNetError(f"unsupported data type {type(data)}")
    out = []
    for name, d in pairs:
        if isinstance(d, NDArray):
            d = d.asnumpy()
        d = np.asarray(d)
        if d.dtype == np.float64:
            d = d.astype(np.float32)
        out.append((name, d))
    return out


class NDArrayIter(DataIter):
    """Batches over in-memory arrays with pad/discard/roll_over handling
    (reference: io.NDArrayIter).

    ``seed`` opts into DETERMINISTIC epochs: epoch e's shuffle order is
    a pure function of (seed, e) instead of the global numpy RNG, which
    is what makes the iterator checkpointable — :meth:`get_cursor`
    captures (epoch, position, seed) and :meth:`set_cursor` replays the
    order chain so a supervised resume sees exactly the batch the
    killed run would have seen next, neither replaying nor skipping
    data (docs/training_resilience.md §3)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", seed=None):
        super().__init__(batch_size)
        self.data = _init_data(data, False, data_name)
        self.label = _init_data(label, True, label_name)
        self.num_data = self.data[0][1].shape[0]
        for name, arr in self.data + self.label:
            if arr.shape[0] != self.num_data:
                raise MXNetError(
                    f"field {name!r} has {arr.shape[0]} samples, expected "
                    f"{self.num_data}")
        if last_batch_handle not in ("pad", "discard", "roll_over"):
            raise MXNetError(
                f"invalid last_batch_handle {last_batch_handle!r}")
        if last_batch_handle == "discard" and self.num_data < batch_size:
            raise MXNetError("not enough data for even one batch")
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self._seed = None if seed is None else int(seed)
        self._epoch = -1    # reset() increments; first epoch is 0
        self._carry = None  # roll_over: sample indices left from last epoch
        self._order = np.arange(self.num_data)
        self.cursor = -batch_size
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(name, (self.batch_size,) + arr.shape[1:],
                         arr.dtype) for name, arr in self.data]

    @property
    def provide_label(self):
        return [DataDesc(name, (self.batch_size,) + arr.shape[1:],
                         arr.dtype) for name, arr in self.label]

    def _epoch_perm(self, epoch):
        """Epoch ``epoch``'s permutation — pure in (seed, epoch)."""
        idx = np.arange(self.num_data)
        if self.shuffle:
            np.random.RandomState([self._seed, epoch]).shuffle(idx)
        return idx

    def reset(self):
        self._epoch += 1
        if self._seed is not None:
            idx = self._epoch_perm(self._epoch)
        else:
            idx = np.arange(self.num_data)
            if self.shuffle:
                np.random.shuffle(idx)
        if self.last_batch_handle == "roll_over" and self._carry is not None:
            # leftover samples from the previous epoch lead this one
            self._order = np.concatenate([self._carry, idx])
            self._carry = None
        else:
            self._order = idx
        self.cursor = -self.batch_size

    # ------------------------------------------------- checkpointable cursor
    def get_cursor(self):
        """Checkpointable position: exactly what :meth:`set_cursor`
        needs to make the NEXT ``next()`` return the same batch an
        uninterrupted run would have returned.  Requires ``seed=``
        when shuffling (the global-RNG order cannot be replayed)."""
        if self.shuffle and self._seed is None:
            raise MXNetError(
                "NDArrayIter.get_cursor: a shuffling iterator is only "
                "checkpointable with seed= (epoch order must be a "
                "pure function of (seed, epoch) to replay on resume)")
        return {"epoch": int(self._epoch), "cursor": int(self.cursor),
                "seed": self._seed, "shuffle": bool(self.shuffle),
                "num_data": int(self.num_data),
                "batch_size": int(self.batch_size),
                "last_batch_handle": self.last_batch_handle}

    def set_cursor(self, state):
        """Rewind/fast-forward to a :meth:`get_cursor` snapshot by
        replaying the deterministic epoch-order chain (roll_over
        carries included).  Refuses a snapshot from a differently
        configured iterator — resuming against different data is the
        silent replay/skip bug this cursor exists to prevent."""
        expected = {"seed": self._seed,
                    "shuffle": bool(self.shuffle),
                    "num_data": int(self.num_data),
                    "batch_size": int(self.batch_size),
                    "last_batch_handle": self.last_batch_handle}
        for key, mine in expected.items():
            if state.get(key) != mine:
                raise MXNetError(
                    f"NDArrayIter.set_cursor: snapshot {key}="
                    f"{state.get(key)!r} does not match this "
                    f"iterator's {mine!r} — refusing a cursor from a "
                    f"different data configuration")
        if self.shuffle and self._seed is None:
            raise MXNetError(
                "NDArrayIter.set_cursor requires seed= when shuffling")
        epoch = int(state["epoch"])
        # replay the order chain from epoch 0: with roll_over, epoch
        # e's head is epoch e-1's leftover tail, so the chain is the
        # only faithful reconstruction
        carry = None
        order = np.arange(self.num_data)
        for e in range(epoch + 1):
            idx = self._epoch_perm(e) if self._seed is not None \
                else np.arange(self.num_data)
            order = np.concatenate([carry, idx]) \
                if (self.last_batch_handle == "roll_over"
                    and carry is not None) else idx
            carry = None
            if self.last_batch_handle == "roll_over":
                leftover = len(order) % self.batch_size
                if leftover:
                    carry = order[len(order) - leftover:]
        self._epoch = epoch
        self._order = order
        # live iteration regenerates the roll_over carry itself at the
        # epoch boundary; a between-steps snapshot never holds one
        self._carry = None
        self.cursor = int(state["cursor"])

    def iter_next(self):
        self.cursor += self.batch_size
        n = len(self._order)
        if self.last_batch_handle == "pad":
            return self.cursor < n
        if self.cursor + self.batch_size <= n:
            return True
        if self.last_batch_handle == "roll_over" and self.cursor < n:
            self._carry = self._order[self.cursor:]
        return False

    def _take(self, arrs):
        n = len(self._order)
        start = self.cursor
        end = start + self.batch_size
        out = []
        for _, arr in arrs:
            if end <= n:
                sel = arr[self._order[start:end]]
            else:  # pad: wrap around to the epoch start
                sel = np.concatenate([arr[self._order[start:]],
                                      arr[self._order[:end - n]]])
            out.append(nd.array(sel, dtype=sel.dtype))
        return out

    def getdata(self):
        return self._take(self.data)

    def getlabel(self):
        return self._take(self.label)

    def getpad(self):
        end = self.cursor + self.batch_size
        if self.last_batch_handle == "pad" and end > len(self._order):
            return end - len(self._order)
        return 0

    def next(self):
        _faults.inject("train.data.next")
        timed = _rm._ENABLED or _tr._ENABLED
        t0 = time.perf_counter() if timed else 0.0
        if not self.iter_next():
            raise StopIteration
        if _rm._ENABLED:
            _rm.IO_BATCHES.inc()
        batch = DataBatch(data=self.getdata(), label=self.getlabel(),
                          pad=self.getpad(), index=None,
                          provide_data=self.provide_data,
                          provide_label=self.provide_label)
        if timed:
            _pa.note_data_wait(t0, time.perf_counter())
        return batch


def _jpeg_dims(buf):
    """(height, width) from a JPEG header without decoding, or None.
    A ~microsecond marker scan that lets the decode path pick a
    DCT-reduced scale before calling imdecode."""
    if len(buf) < 4 or buf[0] != 0xFF or buf[1] != 0xD8:
        return None
    i, n = 2, len(buf)
    while i + 9 < n:
        if buf[i] != 0xFF:
            return None
        m = buf[i + 1]
        if m == 0xFF:                                # fill byte (T.81 B.1.1.2)
            i += 1
            continue
        if m == 0xD9:                                # EOI before any SOF
            return None
        if m in (0xD8, 0x01) or 0xD0 <= m <= 0xD7:   # markers w/o length
            i += 2
            continue
        if 0xC0 <= m <= 0xCF and m not in (0xC4, 0xC8, 0xCC):   # SOFn
            return ((buf[i + 5] << 8) | buf[i + 6],
                    (buf[i + 7] << 8) | buf[i + 8])
        i += 2 + ((buf[i + 2] << 8) | buf[i + 3])
    return None


def _shard_range(n, num_parts, part_index):
    """The reference's num_parts/part_index shard contract."""
    if not 0 <= part_index < num_parts:
        raise MXNetError(
            f"part_index {part_index} out of range for {num_parts} parts")
    per = n // num_parts
    start = per * part_index
    end = per * (part_index + 1) if part_index < num_parts - 1 else n
    return start, end


class CSVIter(NDArrayIter):
    """CSV reader (reference: src/io/iter_csv.cc / io.CSVIter)."""

    def __init__(self, data_csv, data_shape, label_csv=None,
                 label_shape=None, batch_size=1, round_batch=True,
                 num_parts=1, part_index=0, data_name="data",
                 label_name="softmax_label"):
        data = _load_csv(data_csv)
        n = data.shape[0]
        data = data.reshape((n,) + tuple(data_shape))
        if label_csv is not None:
            label = _load_csv(label_csv)
            if label_shape is not None:
                label = label.reshape((n,) + tuple(label_shape))
            else:
                label = label.reshape(n)
        else:
            label = np.zeros(n, dtype=np.float32)
        s, e = _shard_range(n, num_parts, part_index)
        super().__init__(data[s:e], label[s:e], batch_size,
                         last_batch_handle="pad" if round_batch
                         else "discard",
                         data_name=data_name, label_name=label_name)


def _load_csv(path):
    """Numeric CSV → float32 (rows, cols); C++ parser when available
    (reference: iter_csv.cc), numpy fallback."""
    from ..lib import nativelib
    if nativelib.available():
        return nativelib.csv_load(path)
    return np.loadtxt(path, delimiter=",", dtype=np.float32, ndmin=2)


def _read_idx_file(path):
    """MNIST idx format (magic 0x801/0x803 big-endian)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        raw = f.read()
    magic, = struct.unpack(">I", raw[:4])
    ndim = magic & 0xff
    dims = struct.unpack(f">{ndim}I", raw[4:4 + 4 * ndim])
    data = np.frombuffer(raw, dtype=np.uint8, offset=4 + 4 * ndim)
    return data.reshape(dims)


class MNISTIter(DataIter):
    """MNIST idx reader (reference: src/io/iter_mnist.cc)."""

    def __init__(self, image, label, batch_size=128, shuffle=True,
                 flat=False, seed=0, num_parts=1, part_index=0,
                 silent=True):
        super().__init__(batch_size)
        images = _read_idx_file(image).astype(np.float32) / 255.0
        labels = _read_idx_file(label).astype(np.float32)
        if images.shape[0] != labels.shape[0]:
            raise MXNetError("image/label count mismatch")
        s, e = _shard_range(images.shape[0], num_parts, part_index)
        images, labels = images[s:e], labels[s:e]
        if flat:
            images = images.reshape(images.shape[0], -1)
        else:
            images = images[:, None, :, :]  # NCHW
        if shuffle:
            order = np.random.RandomState(seed).permutation(len(images))
            images, labels = images[order], labels[order]
        self._inner = NDArrayIter(images, labels, batch_size,
                                  last_batch_handle="discard")

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


class ImageRecordIter(DataIter):
    """RecordIO image pipeline: shard → decode → augment → batch
    (reference: src/io/iter_image_recordio_2.cc).

    A background producer thread assembles batches ahead of the consumer
    (queue depth ``prefetch_buffer``) and fans decode/augment work out to
    ``preprocess_threads`` pool workers; augmentations cover the default
    ImageAugmenter set (resize, center/rand crop, mirror, mean
    subtraction, scale).
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 path_imgidx=None, shuffle=False, rand_crop=False,
                 rand_mirror=False, mean_r=0.0, mean_g=0.0, mean_b=0.0,
                 scale=1.0, resize=-1, num_parts=1, part_index=0,
                 label_width=1, round_batch=True, seed=0,
                 preprocess_threads=1, prefetch_buffer=4):
        super().__init__(batch_size)
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (C, H, W)")
        self.data_shape = tuple(data_shape)
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.mean = np.array([mean_r, mean_g, mean_b],
                             np.float32).reshape(3, 1, 1)
        self.scale = scale
        self.resize = resize
        self.label_width = label_width
        self.round_batch = round_batch
        self._rng = np.random.RandomState(seed)
        self._shuffle = shuffle

        # index the record file once, then shard
        self._rec = recordio.MXIndexedRecordIO(
            path_imgidx or path_imgrec + ".idx", path_imgrec, "r") \
            if (path_imgidx or os.path.exists(path_imgrec + ".idx")) \
            else None
        self._native = None
        if self._rec is not None and self._rec.keys:
            keys = list(self._rec.keys)
        else:
            # no index: scan once recording offsets.  The C++ scanner
            # (lib/nativelib) walks frames without copying payloads;
            # python fallback reads them all.
            self._rec = None
            from ..lib import nativelib
            if nativelib.available():
                self._native = nativelib.NativeRecordReader(path_imgrec)
                self._offsets = self._native.index().tolist()
            else:
                self._offsets = []
                reader = recordio.MXRecordIO(path_imgrec, "r")
                while True:
                    pos = reader.tell()
                    if reader.read() is None:
                        break
                    self._offsets.append(pos)
                reader.close()
                self._plain_reader = recordio.MXRecordIO(path_imgrec, "r")
            keys = list(range(len(self._offsets)))
        s, e = _shard_range(len(keys), num_parts, part_index)
        self._keys = keys[s:e]
        self._order = list(range(len(self._keys)))
        self._pos = 0
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(max(1, preprocess_threads)) \
            if preprocess_threads > 1 else None
        self._nthreads = max(1, preprocess_threads)
        # native decode tier: whole-batch JPEG decode+resize+crop+mirror
        # on C++ OS threads in ONE call (reference: the C++ worker pool
        # of iter_image_recordio_2.cc).  Non-JPEG payloads and decode
        # failures fall back to the per-image Python path.
        from ..lib import nativelib as _nativelib
        self._native_jpeg = (self.data_shape[0] == 3
                             and _nativelib.jpeg_available())
        self._depth = max(1, prefetch_buffer)
        self._queue = None
        self._producer = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        self._stop_producer()
        if self._shuffle:
            self._rng.shuffle(self._order)
        self._pos = 0
        self._done = False
        self._start_producer()

    # ------------------------------------------------------- prefetch plumbing
    def _start_producer(self):
        self._queue = _queue.Queue(maxsize=self._depth)
        self._stop_evt = threading.Event()

        def produce():
            try:
                while not self._stop_evt.is_set():
                    try:
                        batch = self._next_batch_sync()
                    except StopIteration:
                        self._queue.put(None)
                        return
                    self._queue.put(batch)
            except Exception as e:
                self._queue.put(e)

        self._producer = _engine.make_thread(
            produce, name="mxnet-imgrec-producer", owner="ImageRecordIter")
        self._producer.start()

    def _stop_producer(self):
        if self._producer is None:
            return
        self._stop_evt.set()
        try:
            while True:
                self._queue.get_nowait()
        except _queue.Empty:
            pass
        self._producer.join(timeout=5)
        self._producer = None

    def close(self):
        """Terminal stop: halt the producer and shut down the decode
        pool (``reset()`` restarts the producer; ``close()`` does not).
        Found by mxlint thread-lifecycle: the decode pool's workers are
        non-daemon, so an un-shut-down pool outlives the iterator."""
        self._stop_producer()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
            self._nthreads = 1
        self._done = True

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def next(self):
        _faults.inject("train.data.next")
        timed = _rm._ENABLED or _tr._ENABLED
        t0 = time.perf_counter() if timed else 0.0
        if self._done:
            raise StopIteration
        got = self._queue.get()
        if _rm._ENABLED:
            _rm.IO_PREFETCH_DEPTH.set(self._queue.qsize())
        if got is None:
            self._done = True
            raise StopIteration
        if isinstance(got, Exception):
            self._done = True
            raise got
        if _rm._ENABLED:
            _rm.IO_BATCHES.inc()
        if timed:
            _pa.note_data_wait(t0, time.perf_counter())
        return got

    def iter_next(self):
        raise MXNetError(
            "ImageRecordIter prefetches in the background; use next()")

    # ---------------------------------------------------------- decode path
    def _read_record(self, key):
        if self._rec is not None:
            return self._rec.read_idx(key)
        if self._native is not None:
            return self._native.read_at(self._offsets[key])
        self._plain_reader._f.seek(self._offsets[key])
        return self._plain_reader.read()

    def _decode_one(self, payload, rng):
        import cv2
        if _rm._ENABLED:
            _rm.IO_PYTHON_DECODE.inc()
        header, blob = recordio.unpack(payload)
        # DCT-domain reduced decode: when the source is >= 2x/4x/8x the
        # resize target, libjpeg can IDCT straight to the smaller scale —
        # the single biggest per-image cost is full-resolution decode
        # (reference: iter_image_recordio_2.cc decodes full-size; this is
        # the host-side lever that matters when one core feeds the chip)
        flag = cv2.IMREAD_COLOR
        if self.resize > 0:
            dims = _jpeg_dims(blob)
            if dims is not None:
                short = min(dims)
                for k, f in ((8, cv2.IMREAD_REDUCED_COLOR_8),
                             (4, cv2.IMREAD_REDUCED_COLOR_4),
                             (2, cv2.IMREAD_REDUCED_COLOR_2)):
                    if short >= k * self.resize:
                        flag = f
                        break
        img = cv2.imdecode(np.frombuffer(blob, np.uint8), flag)
        if img is None:
            raise MXNetError(f"record id={header.id}: image decode failed")
        if self.resize > 0:
            h, w = img.shape[:2]
            if h < w:
                new = (int(w * self.resize / h), self.resize)
            else:
                new = (self.resize, int(h * self.resize / w))
            img = cv2.resize(img, new)
        c, th, tw = self.data_shape
        h, w = img.shape[:2]
        if h < th or w < tw:
            img = cv2.resize(img, (max(w, tw), max(h, th)))
            h, w = img.shape[:2]
        if self.rand_crop:
            y = rng.randint(0, h - th + 1)
            x = rng.randint(0, w - tw + 1)
        else:
            y, x = (h - th) // 2, (w - tw) // 2
        img = img[y:y + th, x:x + tw]
        if self.rand_mirror and rng.rand() < 0.5:
            img = img[:, ::-1]
        img = img[:, :, ::-1]  # BGR (cv2) -> RGB
        chw = np.transpose(img, (2, 0, 1)).astype(np.float32)
        chw = (chw - self.mean) * self.scale
        label = np.atleast_1d(np.asarray(header.label, np.float32))
        if label.size < self.label_width:
            raise MXNetError(
                f"record id={header.id} has {label.size} label value(s), "
                f"label_width={self.label_width} requested")
        return chw, label[:self.label_width]

    def _decode_batch_native(self, payloads):
        """Whole-batch decode on the native C++ thread pool.  Returns
        (data, labels) or (None, None) when the batch isn't native-
        eligible (non-JPEG records); individual decode failures are
        re-done on the Python path.  Augmentation randomness (crop
        position fractions, mirror coin flips) is drawn from the
        iterator's seeded RNG here, so determinism semantics match the
        Python tier."""
        from ..lib import nativelib

        headers, blobs = [], []
        for p in payloads:
            hdr, blob = recordio.unpack(p)
            headers.append(hdr)
            blobs.append(blob)
        if not any(b[:2] == b"\xff\xd8" for b in blobs):
            # Zero JPEGs in this batch.  Disable the probe only while
            # we have NEVER seen a JPEG from this shard (first-batch
            # evidence of an all-PNG shard); once any batch has used
            # the native tier, a stray all-PNG batch under shuffle must
            # not turn it off for the rest of the epoch.
            if not getattr(self, "_native_seen_jpeg", False):
                self._native_jpeg = False
            return None, None
        self._native_seen_jpeg = True
        _c, th, tw = self.data_shape
        n = len(blobs)
        if self.rand_crop:
            cy = self._rng.random_sample(n).astype(np.float32)
            cx = self._rng.random_sample(n).astype(np.float32)
        else:
            # negative = center-crop sentinel (integer offset, native side)
            cy = np.full(n, -1.0, np.float32)
            cx = np.full(n, -1.0, np.float32)
        mir = (self._rng.random_sample(n) < 0.5).astype(np.uint8) \
            if self.rand_mirror else np.zeros(n, np.uint8)
        out, status = nativelib.decode_jpeg_batch(
            blobs, self.resize if self.resize > 0 else 0, th, tw,
            cy, cx, mir, self._nthreads)
        if _rm._ENABLED:
            # failed records are re-decoded on the Python path below,
            # where _decode_one counts them
            _rm.IO_NATIVE_DECODE.inc(n - int(np.count_nonzero(status)))
        data = out.astype(np.float32)
        if self.mean.any() or self.scale != 1.0:
            data = (data - self.mean) * self.scale
        labels = np.empty((n, self.label_width), np.float32)
        for i, hdr in enumerate(headers):
            lab = np.atleast_1d(np.asarray(hdr.label, np.float32))
            if lab.size < self.label_width:
                raise MXNetError(
                    f"record id={hdr.id} has {lab.size} label value(s), "
                    f"label_width={self.label_width} requested")
            labels[i] = lab[:self.label_width]
        for i in np.nonzero(status)[0]:
            img, lab = self._decode_one(
                payloads[i],
                np.random.RandomState(self._rng.randint(0, 2**31)))
            data[i] = img
            labels[i] = lab
        return data, labels

    def _next_batch_sync(self):
        """Assemble one batch; record reads stay on the producer thread,
        decode/augment fans out to the worker pool."""
        n = len(self._keys)
        if self._pos >= n:
            raise StopIteration
        idxs = []
        for i in range(self.batch_size):
            j = self._pos + i
            if j < n:
                idxs.append(self._order[j])
            elif self.round_batch:
                idxs.append(self._order[j % n])
            else:
                break
        if not idxs or (len(idxs) < self.batch_size
                        and not self.round_batch):
            raise StopIteration
        pad = self.batch_size - min(n - self._pos, self.batch_size)
        self._pos += self.batch_size
        payloads = [self._read_record(self._keys[k]) for k in idxs]
        data, labels = self._decode_batch_native(payloads) \
            if self._native_jpeg else (None, None)
        if data is None:
            # per-record RNG decided here so pool workers never share state
            rngs = [np.random.RandomState(self._rng.randint(0, 2**31))
                    for _ in idxs]
            if self._pool is not None:
                decoded = list(self._pool.map(self._decode_one, payloads,
                                              rngs))
            else:
                decoded = [self._decode_one(p, r)
                           for p, r in zip(payloads, rngs)]
            data = np.empty((len(idxs),) + self.data_shape, np.float32)
            labels = np.empty((len(idxs), self.label_width), np.float32)
            for i, (img, lab) in enumerate(decoded):
                data[i] = img
                labels[i] = lab
        label_arr = labels[:, 0] if self.label_width == 1 else labels
        return DataBatch(data=[nd.array(data)],
                         label=[nd.array(label_arr)], pad=pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
