"""Misc utilities (reference: python/mxnet/util.py, python/mxnet/name.py,
python/mxnet/attribute.py)."""
from __future__ import annotations

import functools
import threading

from .base import get_env, list_env_vars

__all__ = ["makedirs", "use_np", "np_shape", "np_array", "getenv", "setenv",
           "NameManager", "AttrScope", "as_list"]


def as_list(x):
    """Wrap a non-list in a one-element list (shared helper)."""
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def makedirs(d):
    import os
    os.makedirs(d, exist_ok=True)


def getenv(name):
    return get_env(name)


def setenv(name, value):
    import os
    os.environ[name] = str(value)


def env_info():
    """Document all registered env knobs (reference:
    docs faq/env_var.md — here generated from the registry)."""
    return list_env_vars()


# numpy-compat shims (the mx.np layer is numpy-semantics by construction on
# JAX, so these are no-ops kept for API parity)
def use_np(func):
    return func


def np_shape(active=True):
    import contextlib
    return contextlib.nullcontext()


np_array = np_shape


class NameManager:
    """Auto-naming for layers/symbols (reference: python/mxnet/name.py)."""

    _current = threading.local()

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name:
            return name
        self._counter.setdefault(hint, 0)
        name = f"{hint}{self._counter[hint]}"
        self._counter[hint] += 1
        return name

    @classmethod
    def current(cls) -> "NameManager":
        if not hasattr(cls._current, "value"):
            cls._current.value = NameManager()
        return cls._current.value

    def __enter__(self):
        self._old = getattr(NameManager._current, "value", None)
        NameManager._current.value = self
        return self

    def __exit__(self, *exc):
        NameManager._current.value = self._old
        return False


class AttrScope:
    """Attribute scoping for symbols, incl. ctx_group model-parallel
    annotations (reference: python/mxnet/attribute.py; SURVEY.md P7).
    On TPU, ctx_group maps to sharding annotations — see parallel/."""

    _current = threading.local()

    def __init__(self, **attrs):
        self._attrs = {k: str(v) for k, v in attrs.items()}

    @classmethod
    def current_attrs(cls):
        scope = getattr(cls._current, "value", None)
        return dict(scope._attrs) if scope else {}

    def __enter__(self):
        self._old = getattr(AttrScope._current, "value", None)
        merged = dict(self._old._attrs) if self._old else {}
        merged.update(self._attrs)
        self._merged_scope = AttrScope(**merged)
        AttrScope._current.value = self._merged_scope
        return self

    def __exit__(self, *exc):
        AttrScope._current.value = self._old
        return False
