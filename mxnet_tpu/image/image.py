"""mx.image: decode / resize / augment pipeline.

Reference surface: ``python/mxnet/image/image.py`` (imread/imdecode,
resize/crop helpers, Augmenter classes, CreateAugmenter, ImageIter —
SURVEY.md 2.2 image row).

TPU-native split of labor: decode + augmentation are *host-side* CPU work
feeding the device (as in the reference, where this wraps OpenCV) — so the
implementation is numpy with a codec backend chain (cv2 → PIL → a built-in
pure-numpy PNG codec), never a device computation.  Batches leave this
module as NDArrays ready for a single host→HBM transfer.
"""
from __future__ import annotations

import os
import random as pyrandom
import struct
import zlib
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["imread", "imdecode", "imencode", "imwrite", "imresize",
           "resize_short", "fixed_crop", "center_crop", "random_crop",
           "random_size_crop", "color_normalize",
           "Augmenter", "SequentialAug", "RandomOrderAug", "ResizeAug",
           "ForceResizeAug", "RandomCropAug", "CenterCropAug",
           "RandomSizedCropAug", "HorizontalFlipAug", "CastAug",
           "ColorNormalizeAug", "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug", "HueJitterAug", "ColorJitterAug",
           "LightingAug", "RandomGrayAug", "CreateAugmenter", "ImageIter"]


# ---------------------------------------------------------------------------
# codec backends
# ---------------------------------------------------------------------------

def _backend():
    try:
        import cv2
        return "cv2"
    except ImportError:
        pass
    try:
        import PIL.Image  # noqa: F401
        return "pil"
    except ImportError:
        return "numpy"


_BACKEND = _backend()


def _png_decode(data: bytes) -> np.ndarray:
    """Pure-numpy PNG decoder: 8-bit gray/RGB/RGBA, non-interlaced.
    Fallback so the framework decodes its own PNGs with zero deps."""
    if data[:8] != b"\x89PNG\r\n\x1a\n":
        raise MXNetError("not a PNG file")
    pos, w = 8, None
    idat = b""
    while pos < len(data):
        (length,), ctype = struct.unpack(">I", data[pos:pos + 4]), \
            data[pos + 4:pos + 8]
        chunk = data[pos + 8:pos + 8 + length]
        if ctype == b"IHDR":
            w, h, depth, color, _comp, _filt, interlace = \
                struct.unpack(">IIBBBBB", chunk)
            if depth != 8 or interlace:
                raise MXNetError("numpy PNG codec: 8-bit non-interlaced only")
            channels = {0: 1, 2: 3, 4: 2, 6: 4}.get(color)
            if channels is None:
                raise MXNetError(f"unsupported PNG color type {color}")
        elif ctype == b"IDAT":
            idat += chunk
        elif ctype == b"IEND":
            break
        pos += 12 + length
    raw = np.frombuffer(zlib.decompress(idat), dtype=np.uint8)
    stride = w * channels
    raw = raw.reshape(h, stride + 1)
    filters, lines = raw[:, 0], raw[:, 1:].astype(np.int32)
    out = np.zeros((h, stride), dtype=np.int32)
    c = channels
    for y in range(h):
        line = lines[y].copy()
        f = filters[y]
        prev = out[y - 1] if y else np.zeros(stride, np.int32)
        if f == 0:
            out[y] = line
        elif f == 2:      # up
            out[y] = (line + prev) & 0xFF
        elif f in (1, 3, 4):
            for x in range(stride):
                a = out[y, x - c] if x >= c else 0
                b = prev[x]
                if f == 1:
                    pred = a
                elif f == 3:
                    pred = (a + b) // 2
                else:
                    cc = prev[x - c] if x >= c else 0
                    p = a + b - cc
                    pa, pb, pc = abs(p - a), abs(p - b), abs(p - cc)
                    pred = a if (pa <= pb and pa <= pc) else \
                        (b if pb <= pc else cc)
                out[y, x] = (line[x] + pred) & 0xFF
        else:
            raise MXNetError(f"bad PNG filter {f}")
    img = out.astype(np.uint8).reshape(h, w, channels)
    return img


def _png_encode(img: np.ndarray) -> bytes:
    """Pure-numpy PNG encoder (filter 0 scanlines)."""
    if img.ndim == 2:
        img = img[:, :, None]
    h, w, c = img.shape
    color = {1: 0, 2: 4, 3: 2, 4: 6}[c]
    ihdr = struct.pack(">IIBBBBB", w, h, 8, color, 0, 0, 0)
    scan = np.concatenate(
        [np.zeros((h, 1), np.uint8), img.reshape(h, w * c)], axis=1)
    idat = zlib.compress(scan.tobytes(), 6)

    def chunk(ctype, payload):
        body = ctype + payload
        return struct.pack(">I", len(payload)) + body + \
            struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF)

    return (b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr) +
            chunk(b"IDAT", idat) + chunk(b"IEND", b""))


def imdecode(buf, flag=1, to_rgb=True, **kwargs) -> NDArray:
    """Decode an encoded image buffer to an HWC uint8 NDArray
    (reference: mx.image.imdecode over cv2.imdecode).
    flag: 1=color, 0=grayscale."""
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().tobytes()
    data = bytes(buf)
    if _BACKEND == "cv2":
        import cv2
        img = cv2.imdecode(np.frombuffer(data, np.uint8),
                           cv2.IMREAD_COLOR if flag else
                           cv2.IMREAD_GRAYSCALE)
        if img is None:
            raise MXNetError("imdecode: decode failed")
        if flag and to_rgb:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        if not flag:
            img = img[:, :, None]
    elif _BACKEND == "pil":
        import io as _io
        import PIL.Image
        pimg = PIL.Image.open(_io.BytesIO(data))
        pimg = pimg.convert("RGB" if flag else "L")
        img = np.asarray(pimg)
        if not flag:
            img = img[:, :, None]
    else:
        img = _png_decode(data)
        if img.shape[2] == 2:           # gray+alpha: drop alpha
            img = img[:, :, :1]
        if flag and img.shape[2] == 1:
            img = np.repeat(img, 3, axis=2)
        elif flag and img.shape[2] == 4:
            img = img[:, :, :3]
        elif not flag and img.shape[2] != 1:
            img = img[:, :, :3].mean(axis=2, keepdims=True) \
                .astype(np.uint8)
    return nd.array(img, dtype="uint8")


def imread(filename, flag=1, to_rgb=True, **kwargs) -> NDArray:
    """Read an image file to an HWC uint8 NDArray (reference: imread)."""
    if not os.path.exists(filename):
        raise MXNetError(f"imread: no such file {filename!r}")
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def imencode(img, ext=".png", quality=95) -> bytes:
    """Encode an HWC uint8 image (helper; reference uses cv2.imencode)."""
    arr = img.asnumpy() if isinstance(img, NDArray) else np.asarray(img)
    if _BACKEND == "cv2":
        import cv2
        enc = arr[:, :, ::-1] if arr.ndim == 3 and arr.shape[2] == 3 else arr
        params = [cv2.IMWRITE_JPEG_QUALITY, quality] \
            if ext in (".jpg", ".jpeg") else []
        ok, buf = cv2.imencode(ext, enc, params)
        if not ok:
            raise MXNetError("imencode failed")
        return buf.tobytes()
    if _BACKEND == "pil" and ext != ".png":
        import io as _io
        import PIL.Image
        bio = _io.BytesIO()
        PIL.Image.fromarray(arr.squeeze()).save(bio, format="JPEG",
                                                quality=quality)
        return bio.getvalue()
    return _png_encode(arr)


def imwrite(filename, img, quality=95):
    ext = os.path.splitext(filename)[1].lower() or ".png"
    with open(filename, "wb") as f:
        f.write(imencode(img, ext=ext, quality=quality))


# ---------------------------------------------------------------------------
# geometry
# ---------------------------------------------------------------------------

def imresize(src, w, h, interp=1) -> NDArray:
    """Resize HWC image to (h, w) (reference: mx.image.imresize)."""
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    if _BACKEND == "cv2":
        import cv2
        interp_map = {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR,
                      2: cv2.INTER_CUBIC, 3: cv2.INTER_AREA,
                      4: cv2.INTER_LANCZOS4}
        out = cv2.resize(arr, (w, h), interpolation=interp_map.get(
            interp, cv2.INTER_LINEAR))
        if out.ndim == 2:
            out = out[:, :, None]
    elif _BACKEND == "pil":
        import PIL.Image
        mode_map = {0: PIL.Image.NEAREST, 1: PIL.Image.BILINEAR,
                    2: PIL.Image.BICUBIC}
        squeezed = arr.squeeze()
        out = np.asarray(PIL.Image.fromarray(squeezed).resize(
            (w, h), mode_map.get(interp, PIL.Image.BILINEAR)))
        if out.ndim == 2:
            out = out[:, :, None]
        if arr.ndim == 3 and out.ndim == 2:
            out = out[:, :, None]
    else:
        ys = (np.arange(h) * arr.shape[0] / h).astype(np.int64)
        xs = (np.arange(w) * arr.shape[1] / w).astype(np.int64)
        out = arr[ys][:, xs]
    return nd.array(out, dtype=str(arr.dtype))


def resize_short(src, size, interp=2) -> NDArray:
    """Resize so the shorter edge becomes `size` (reference: resize_short)."""
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(arr, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2) -> NDArray:
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp).asnumpy()
    return nd.array(out, dtype=str(arr.dtype))


def center_crop(src, size, interp=2):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    return fixed_crop(arr, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    return fixed_crop(arr, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random area+aspect crop (reference: random_size_crop)."""
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(*area) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        aspect = np.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * aspect)))
        new_h = int(round(np.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            return fixed_crop(arr, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(arr, size, interp)


def color_normalize(src, mean, std=None):
    """(src - mean) / std in float32 (reference: color_normalize)."""
    arr = src.asnumpy().astype(np.float32) if isinstance(src, NDArray) \
        else np.asarray(src, np.float32)
    mean = np.asarray(mean, np.float32)
    arr = arr - mean
    if std is not None:
        arr = arr / np.asarray(std, np.float32)
    return nd.array(arr)


# ---------------------------------------------------------------------------
# augmenters
# ---------------------------------------------------------------------------

class Augmenter:
    """Image augmenter base (reference: image.Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src: NDArray) -> NDArray:
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts: List[Augmenter]):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts: List[Augmenter]):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size, self.area, self.ratio, self.interp = \
            size, area, ratio, interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return nd.array(src.asnumpy()[:, ::-1].copy(),
                            dtype=str(src.dtype))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(typ=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean, self.std = mean, std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return nd.array(src.asnumpy().astype(np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        arr = src.asnumpy().astype(np.float32)
        gray = (arr * self._coef).sum(axis=2).mean()
        return nd.array(arr * alpha + gray * (1 - alpha))


class SaturationJitterAug(Augmenter):
    _coef = ContrastJitterAug._coef

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        arr = src.asnumpy().astype(np.float32)
        gray = (arr * self._coef).sum(axis=2, keepdims=True)
        return nd.array(arr * alpha + gray * (1 - alpha))


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]], np.float32)
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]], np.float32)

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                      np.float32)
        t = self.ityiq @ bt @ self.tyiq
        arr = src.asnumpy().astype(np.float32)
        return nd.array(arr @ t.T)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA-noise lighting (reference: LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__()
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,)) \
            .astype(np.float32)
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return nd.array(src.asnumpy().astype(np.float32) + rgb)


class RandomGrayAug(Augmenter):
    _coef = np.array([[[0.299], [0.587], [0.114]]], np.float32) \
        .reshape(1, 1, 3)

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            arr = src.asnumpy().astype(np.float32)
            gray = (arr * self._coef).sum(axis=2, keepdims=True)
            return nd.array(np.repeat(gray, 3, axis=2))
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmenter pipeline (reference: CreateAugmenter)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and np.any(np.asarray(mean) != 0):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


# ---------------------------------------------------------------------------
# ImageIter
# ---------------------------------------------------------------------------

class ImageIter:
    """Python-side image iterator over RecordIO or an image list
    (reference: mx.image.ImageIter).  Yields NCHW float batches.

    The C++-tier equivalent (threaded decode + prefetch) is
    ``mxnet_tpu.io.ImageRecordIter``; this class is the flexible
    python-augmenter variant, mirroring the reference's split.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, dtype="float32", last_batch_handle="pad",
                 **kwargs):
        from ..io.io import DataDesc, DataBatch
        if len(data_shape) != 3:
            raise MXNetError("data_shape must be (C, H, W)")
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.dtype = dtype
        self._batch_cls = DataBatch
        self.provide_data = [DataDesc("data",
                                      (batch_size,) + self.data_shape,
                                      dtype)]
        lshape = (batch_size,) if label_width == 1 \
            else (batch_size, label_width)
        self.provide_label = [DataDesc("softmax_label", lshape, "float32")]

        self._rec = None
        self.imglist = []
        if path_imgrec is not None:
            from .. import recordio
            idx_path = path_imgrec[:-4] + ".idx" \
                if path_imgrec.endswith(".rec") else path_imgrec + ".idx"
            if os.path.exists(idx_path):
                self._rec = recordio.MXIndexedRecordIO(idx_path,
                                                      path_imgrec, "r")
                self._keys = list(self._rec.keys)
            else:
                self._rec = recordio.MXRecordIO(path_imgrec, "r")
                self._keys = None
                self._records = []
                while True:
                    s = self._rec.read()
                    if s is None:
                        break
                    self._records.append(s)
                self._keys = list(range(len(self._records)))
        elif path_imglist is not None or imglist is not None:
            if imglist is None:
                with open(path_imglist) as f:
                    imglist = []
                    for line in f:
                        parts = line.strip().split("\t")
                        imglist.append([float(x) for x in parts[1:-1]]
                                       + [parts[-1]])
            for entry in imglist:
                *labels, fname = entry
                if path_root is not None:
                    fname = os.path.join(path_root, fname)
                self.imglist.append((np.array(labels, np.float32), fname))
            self._keys = list(range(len(self.imglist)))
        else:
            raise MXNetError(
                "ImageIter needs path_imgrec, path_imglist or imglist")

        n = len(self._keys)
        s = n * part_index // num_parts
        e = n * (part_index + 1) // num_parts
        self._keys = self._keys[s:e]
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_resize", "rand_mirror",
                         "mean", "std", "brightness", "contrast",
                         "saturation", "hue", "pca_noise", "rand_gray",
                         "inter_method")})
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self._order = list(range(len(self._keys)))
        self.reset()

    def reset(self):
        if self.shuffle:
            pyrandom.shuffle(self._order)
        self._cursor = 0

    def _read_one(self, idx):
        from .. import recordio as rio
        key = self._keys[idx]
        if self._rec is not None:
            if hasattr(self, "_records"):
                s = self._records[key]
            else:
                s = self._rec.read_idx(key)
            header, payload = rio.unpack(s)
            label = np.atleast_1d(np.asarray(header.label, np.float32))
            img = imdecode(payload)
        else:
            label, fname = self.imglist[key]
            img = imread(fname)
        for aug in self.auglist:
            img = aug(img)
        arr = img.asnumpy()
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.shape[2] != self.data_shape[0] and \
                self.data_shape[0] == 3 and arr.shape[2] == 1:
            arr = np.repeat(arr, 3, axis=2)
        return arr.transpose(2, 0, 1).astype(self.dtype), label

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        n = len(self._order)
        if self._cursor >= n:
            raise StopIteration
        c = self.data_shape[0]
        data = np.zeros((self.batch_size,) + self.data_shape, self.dtype)
        labels = np.zeros((self.batch_size, self.label_width), np.float32)
        i = 0
        pad = 0
        while i < self.batch_size:
            if self._cursor >= n:
                if self.last_batch_handle == "discard":
                    raise StopIteration
                pad = self.batch_size - i
                for j in range(i, self.batch_size):   # wrap-pad
                    data[j], labels[j] = data[j % max(i, 1)], \
                        labels[j % max(i, 1)]
                break
            arr, label = self._read_one(self._order[self._cursor])
            if arr.shape != self.data_shape:
                raise MXNetError(
                    f"augmented image shape {arr.shape} != data_shape "
                    f"{self.data_shape}; add a Resize/Crop augmenter")
            data[i] = arr
            labels[i, :len(label)] = label[:self.label_width]
            self._cursor += 1
            i += 1
        lab = labels[:, 0] if self.label_width == 1 else labels
        return self._batch_cls(data=[nd.array(data)],
                               label=[nd.array(lab)], pad=pad,
                               provide_data=self.provide_data,
                               provide_label=self.provide_label)
