"""Detection augmenters + ImageDetIter (reference:
``python/mxnet/image/detection.py`` — ``DetAugmenter`` subclasses,
``CreateDetAugmenter``, ``ImageDetIter``; SURVEY.md §2.2 image row
"detection aug").

Host-side data path (numpy), like the rest of the image module: these
run in loader workers, not on the TPU.  Labels are (N, 5+) float rows
``[cls, xmin, ymin, xmax, ymax, ...]`` with coordinates normalized to
[0, 1]; every geometric augmenter transforms image and boxes together.
"""
from __future__ import annotations

import random as pyrandom
from typing import List

import numpy as np

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray
from .image import (Augmenter, CastAug, ColorJitterAug, ColorNormalizeAug,
                    ForceResizeAug, imresize)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Detection augmenter base: ``(src, label) -> (src, label)``
    (reference: image.detection.DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__, self._kwargs])

    def __call__(self, src: NDArray, label: np.ndarray):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only Augmenter that leaves geometry unchanged
    (color/cast/normalize) into the detection pipeline."""

    def __init__(self, augmenter: Augmenter):
        if not isinstance(augmenter, Augmenter):
            raise MXNetError("DetBorrowAug wraps an image Augmenter")
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one of ``aug_list`` (or skip) per sample."""

    def __init__(self, aug_list: List[DetAugmenter], skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or pyrandom.random() < self.skip_prob:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and box x-coordinates with probability ``p``."""

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = nd.array(src.asnumpy()[:, ::-1].copy(),
                           dtype=str(src.dtype))
            label = label.copy()
            valid = label[:, 0] >= 0
            x0 = label[valid, 1].copy()
            label[valid, 1] = 1.0 - label[valid, 3]
            label[valid, 3] = 1.0 - x0
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping a minimum object overlap (SSD-style
    min-IoU sampling; reference: DetRandomCropAug).

    Boxes are clipped to the crop; objects whose center falls outside
    are dropped (cls set to -1)."""

    def __init__(self, min_object_covered=0.3, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.3, 1.0), max_attempts=20):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        H, W = src.shape[0], src.shape[1]
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = pyrandom.uniform(*self.aspect_ratio_range)
            cw = min(1.0, np.sqrt(area * ratio))
            ch = min(1.0, np.sqrt(area / ratio))
            cx = pyrandom.uniform(0, 1.0 - cw)
            cy = pyrandom.uniform(0, 1.0 - ch)
            new_label = self._crop_boxes(label, cx, cy, cw, ch)
            if (new_label[:, 0] >= 0).any() or not \
                    (label[:, 0] >= 0).any():
                x0, y0 = int(cx * W), int(cy * H)
                x1, y1 = int((cx + cw) * W), int((cy + ch) * H)
                img = src.asnumpy()[y0:max(y1, y0 + 1),
                                    x0:max(x1, x0 + 1)]
                return nd.array(img, dtype=str(src.dtype)), new_label
        return src, label

    def _crop_boxes(self, label, cx, cy, cw, ch):
        out = label.copy()
        for i in range(label.shape[0]):
            if label[i, 0] < 0:
                continue
            bx0, by0, bx1, by1 = label[i, 1:5]
            ctr_x, ctr_y = (bx0 + bx1) / 2, (by0 + by1) / 2
            # coverage of the object by the crop
            ix = max(0.0, min(bx1, cx + cw) - max(bx0, cx))
            iy = max(0.0, min(by1, cy + ch) - max(by0, cy))
            barea = max(1e-12, (bx1 - bx0) * (by1 - by0))
            covered = ix * iy / barea
            inside = (cx <= ctr_x <= cx + cw) and (cy <= ctr_y <= cy + ch)
            if not inside or covered < self.min_object_covered:
                out[i, 0] = -1.0
                continue
            out[i, 1] = np.clip((bx0 - cx) / cw, 0, 1)
            out[i, 2] = np.clip((by0 - cy) / ch, 0, 1)
            out[i, 3] = np.clip((bx1 - cx) / cw, 0, 1)
            out[i, 4] = np.clip((by1 - cy) / ch, 0, 1)
        return out


class DetRandomPadAug(DetAugmenter):
    """Zoom-out: place the image on a larger filled canvas and shrink
    boxes accordingly (reference: DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=20,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.area_range = area_range
        self.aspect_ratio_range = aspect_ratio_range
        self.pad_val = pad_val

    def __call__(self, src, label):
        img = src.asnumpy()
        H, W = img.shape[0], img.shape[1]
        scale = pyrandom.uniform(*self.area_range)
        if scale <= 1.0:
            return src, label
        # canvas aspect sampled from aspect_ratio_range (reference
        # samples a ratio and sizes the canvas anisotropically)
        ratio = pyrandom.uniform(*self.aspect_ratio_range)
        new_h = int(H * np.sqrt(scale / ratio))
        new_w = int(W * np.sqrt(scale * ratio))
        new_h, new_w = max(new_h, H), max(new_w, W)
        off_y = pyrandom.randint(0, new_h - H)
        off_x = pyrandom.randint(0, new_w - W)
        canvas = np.empty((new_h, new_w) + img.shape[2:], img.dtype)
        canvas[...] = np.asarray(self.pad_val,
                                 img.dtype)[:img.shape[2] if img.ndim == 3
                                            else 1]
        canvas[off_y:off_y + H, off_x:off_x + W] = img
        out = label.copy()
        valid = out[:, 0] >= 0
        out[valid, 1] = (out[valid, 1] * W + off_x) / new_w
        out[valid, 3] = (out[valid, 3] * W + off_x) / new_w
        out[valid, 2] = (out[valid, 2] * H + off_y) / new_h
        out[valid, 4] = (out[valid, 4] * H + off_y) / new_h
        return nd.array(canvas, dtype=str(src.dtype)), out


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0,
                       min_object_covered=0.3,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.3, 3.0), pad_val=(127, 127, 127),
                       **kwargs):
    """Standard detection pipeline (reference: CreateDetAugmenter)."""
    auglist: List[DetAugmenter] = []
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])))
        auglist.append(DetRandomSelectAug([crop], 1.0 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(1.0, area_range[0]), area_range[1]),
                              pad_val=pad_val)
        auglist.append(DetRandomSelectAug([pad], 1.0 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    # geometry is settled: force the output size
    auglist.append(DetBorrowAug(ForceResizeAug(
        (data_shape[2], data_shape[1]))))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(ColorJitterAug(
            brightness, contrast, saturation)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is not None or std is not None:
        if mean is True:
            mean = np.array([123.68, 116.28, 103.53])
        if std is True:
            std = np.array([58.395, 57.12, 57.375])
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter:
    """Detection batches over RecordIO / image lists (reference:
    mx.image.ImageDetIter).  Yields data (B, C, H, W) and padded labels
    (B, max_objects, 5) with unused rows = -1."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 imglist=None, aug_list=None, shuffle=False,
                 max_objects=16, dtype="float32", **kwargs):
        from ..io.io import DataDesc
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.max_objects = max_objects
        self.dtype = dtype
        self._shuffle = shuffle
        self.auglist = aug_list if aug_list is not None else \
            CreateDetAugmenter(data_shape)
        self.provide_data = [DataDesc("data",
                                      (batch_size,) + self.data_shape,
                                      dtype)]
        self.provide_label = [DataDesc("label",
                                       (batch_size, max_objects, 5),
                                       "float32")]
        # samples: list of (image NDArray | bytes, label np (N,5))
        self._samples = []
        if imglist is not None:
            for img, label in imglist:
                self._samples.append((img, np.asarray(label, np.float32)
                                      .reshape(-1, 5)))
        elif path_imgrec is not None:
            self._load_rec(path_imgrec)
        else:
            raise MXNetError("ImageDetIter needs path_imgrec or imglist")
        self._order = list(range(len(self._samples)))
        self.reset()

    def _load_rec(self, path):
        from .. import recordio
        from .image import imdecode
        rec = recordio.MXRecordIO(path, "r")
        while True:
            s = rec.read()
            if s is None:
                break
            header, img_bytes = recordio.unpack(s)
            flat = np.asarray(header.label, np.float32)
            # reference det-record layout: flat[0] = header WIDTH (number
            # of leading header fields incl. itself), flat[1] = object
            # row width; object rows start at flat[header_width].
            # Accept a plain (N*5,) label too.  When both layouts parse
            # (ambiguous), prefer the one that yields object rows, then
            # the header layout (upstream canonical).
            header_ok = (
                flat.size >= 2 and float(flat[0]).is_integer()
                and 2 <= int(flat[0]) <= flat.size
                and float(flat[1]).is_integer() and int(flat[1]) >= 5
                and (flat.size - int(flat[0])) % int(flat[1]) == 0)
            plain_ok = flat.size > 0 and flat.size % 5 == 0
            header_rows = ((flat.size - int(flat[0])) // int(flat[1])
                           if header_ok else 0)
            if header_ok and (header_rows > 0 or not plain_ok):
                header_width = int(flat[0])
                obj_width = int(flat[1])
                objs = flat[header_width:].reshape(-1, obj_width)[:, :5]
            elif plain_ok:
                objs = flat.reshape(-1, 5)
            else:
                raise MXNetError(
                    f"ImageDetIter: cannot parse det-record label of "
                    f"size {flat.size} (head {flat[:4].tolist()}): "
                    f"expected [header_width, obj_width, ...header..., "
                    f"obj rows] with objects starting at "
                    f"flat[header_width], or a plain (N*5,) "
                    f"[cls, x0, y0, x1, y1] list.  (Records written "
                    f"against this package's pre-r3 nonstandard layout "
                    f"— objects hard-coded at flat[2:] — must be "
                    f"re-packed with the standard header, e.g. "
                    f"[2, 5, cls, x0, y0, x1, y1].)")
            self._samples.append((imdecode(img_bytes),
                                  objs.astype(np.float32)))
        rec.close()

    def reset(self):
        self._cursor = 0
        if self._shuffle:
            pyrandom.shuffle(self._order)

    def __iter__(self):
        return self

    def next(self):
        return self.__next__()

    def __next__(self):
        from ..io.io import DataBatch
        from .image import imdecode
        if self._cursor >= len(self._samples):
            raise StopIteration
        C, H, W = self.data_shape
        data = np.zeros((self.batch_size, H, W, C), np.float32)
        labels = np.full((self.batch_size, self.max_objects, 5), -1.0,
                         np.float32)
        pad = 0
        for i in range(self.batch_size):
            if self._cursor >= len(self._samples):
                pad += 1
                continue
            img, label = self._samples[self._order[self._cursor]]
            self._cursor += 1
            if isinstance(img, (bytes, bytearray)):
                img = imdecode(img)
            label = label.copy()
            for aug in self.auglist:
                img, label = aug(img, label) if isinstance(
                    aug, DetAugmenter) else (aug(img), label)
            arr = img.asnumpy().astype(np.float32)
            if arr.ndim == 2:
                arr = arr[:, :, None]
            data[i, :arr.shape[0], :arr.shape[1], :arr.shape[2]] = \
                arr[:H, :W, :C]
            n = min(label.shape[0], self.max_objects)
            labels[i, :n] = label[:n, :5]
        batch = DataBatch(
            data=[nd.array(data.transpose(0, 3, 1, 2), dtype=self.dtype)],
            label=[nd.array(labels)], pad=pad)
        return batch
