"""RecordIO: the reference's packed binary record container.

Reference surface: ``python/mxnet/recordio.py`` + dmlc-core's
``include/dmlc/recordio.h`` (SURVEY.md §2.1 dmlc-core row, §2.1 Data
iterators row).  The on-disk format is kept byte-compatible so existing
``.rec``/``.idx`` datasets (im2rec output) load unchanged:

- record frame: ``[magic:u32][lrec:u32][payload][pad to 4B]`` where
  ``lrec = cflag<<29 | len``; payloads containing the magic word are split
  into multipart records (cflag 1/2/3) exactly like dmlc::RecordIOWriter.
- image record payload: ``IRHeader`` (flag, label, id, id2) + image bytes;
  ``flag > 0`` carries that many extra label floats.

Implementation is pure Python over buffered file IO — the decode/augment
hot loop lives device-side (jax) and in cv2/PIL, so a C++ reader is not
the bottleneck it was for the reference's OpenCV-on-CPU pipeline.
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LEN_MASK = (1 << 29) - 1
_MAGIC_BYTES = struct.pack("<I", _MAGIC)


def _pad4(n):
    return (4 - n % 4) % 4


class MXRecordIO:
    """Sequential record reader/writer (reference: MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        if flag not in ("r", "w"):
            raise MXNetError(f"invalid flag {flag!r} (use 'r' or 'w')")
        self.open()

    def open(self):
        self._f = open(self.uri, "rb" if self.flag == "r" else "wb")
        self._is_open = True

    def close(self):
        # mxlint: disable=atomicity (contract: a reader/writer is
        # owned by one thread; close() only races itself when that
        # ownership contract is already broken)
        if self._is_open:
            self._f.close()
            self._is_open = False

    def reset(self):
        self.close()
        self.open()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def tell(self):
        return self._f.tell()

    # ------------------------------------------------------------- write
    def write(self, buf: bytes):
        if self.flag != "w":
            raise MXNetError("record file opened read-only")
        # split payload at embedded magic words (dmlc multipart framing)
        parts = buf.split(_MAGIC_BYTES)
        n = len(parts)
        for i, part in enumerate(parts):
            if n == 1:
                cflag = 0
            elif i == 0:
                cflag = 1
            elif i == n - 1:
                cflag = 3
            else:
                cflag = 2
            lrec = (cflag << 29) | len(part)
            self._f.write(_MAGIC_BYTES)
            self._f.write(struct.pack("<I", lrec))
            self._f.write(part)
            self._f.write(b"\x00" * _pad4(len(part)))

    # -------------------------------------------------------------- read
    def read(self):
        """Next record payload, or None at EOF."""
        if self.flag != "r":
            raise MXNetError("record file opened write-only")
        chunks = []
        while True:
            head = self._f.read(8)
            if len(head) == 0 and not chunks:
                return None
            if len(head) < 8:
                raise MXNetError("truncated record header")
            magic, lrec = struct.unpack("<II", head)
            if magic != _MAGIC:
                raise MXNetError(
                    f"bad record magic 0x{magic:08x} at "
                    f"{self._f.tell() - 8}")
            cflag = (lrec >> 29) & 7
            length = lrec & _LEN_MASK
            data = self._f.read(length)
            if len(data) < length:
                raise MXNetError("truncated record payload")
            self._f.read(_pad4(length))
            chunks.append(data)
            if cflag in (0, 3):
                if cflag == 0 and len(chunks) > 1:
                    raise MXNetError("dangling multipart record")
                break
        return _MAGIC_BYTES.join(chunks)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access records via a ``key\\tpos`` .idx sidecar
    (reference: MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.key_type = key_type
        self.idx = {}
        self.keys = []
        super().__init__(uri, flag)
        if flag == "r" and os.path.exists(idx_path):
            with open(idx_path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    key, pos = line.strip().split("\t")
                    key = key_type(key)
                    self.idx[key] = int(pos)
                    self.keys.append(key)

    def close(self):
        if getattr(self, "_is_open", False) and self.flag == "w":
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def read_idx(self, idx):
        self._f.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


# --------------------------------------------------------------------------
# image record payloads
# --------------------------------------------------------------------------
IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Serialize header + raw payload (reference: recordio.pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (list, tuple, np.ndarray)):
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0.0)
        return struct.pack(_IR_FORMAT, *header) + label.tobytes() + s
    return struct.pack(_IR_FORMAT, *header) + s


def unpack(s: bytes):
    """-> (IRHeader, payload) (reference: recordio.unpack)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an HWC uint8 image and pack it (reference: pack_img)."""
    import cv2
    if img_fmt in (".jpg", ".jpeg"):
        params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt == ".png":
        params = [cv2.IMWRITE_PNG_COMPRESSION, quality // 10]
    else:
        raise MXNetError(f"unsupported image format {img_fmt!r}")
    ok, buf = cv2.imencode(img_fmt, img, params)
    if not ok:
        raise MXNetError("image encode failed")
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=1):
    """-> (IRHeader, HWC ndarray) (reference: unpack_img)."""
    import cv2
    header, payload = unpack(s)
    img = cv2.imdecode(np.frombuffer(payload, dtype=np.uint8), iscolor)
    if img is None:
        raise MXNetError("image decode failed")
    return header, img
