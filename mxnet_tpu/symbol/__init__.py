"""``mx.sym`` namespace: symbolic graph building.

Reference: ``python/mxnet/symbol/`` over nnvm (SURVEY.md 2.2).  Op functions
are generated from the same registry as mx.nd (single registry serving both
paths, like NNVM).
"""
from __future__ import annotations

import sys
import types

from .symbol import Symbol, var, Variable, Group, load, load_json, zeros, ones
from ..ops import registry as _reg
from .symbol import invoke_symbolic as _invoke_symbolic

op = types.ModuleType(__name__ + ".op")
op.__doc__ = "Auto-generated symbolic operator functions."
for _name in _reg.list_ops():
    setattr(op, _name, _reg.make_frontend(_reg.get_op(_name)))
sys.modules[op.__name__] = op

_g = globals()
for _name in _reg.list_ops():
    if _name not in _g:
        _g[_name] = getattr(op, _name)
