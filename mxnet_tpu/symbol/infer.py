"""Graph shape/dtype inference (the nnvm InferShape/InferType passes).

Reference: ``src/nnvm/plan_memory.cc`` + per-op ``FInferShape``/``FInferType``
attrs (SURVEY.md 2.1 "Graph IR").  The reference runs bidirectional
per-op inference so ``simple_bind`` can materialize parameter arrays from
data shapes alone.

TPU-native split of labor:
- *forward* inference (inputs known -> output shapes) is delegated to
  ``jax.eval_shape`` over the op's real JAX body — the op function IS its
  shape function, so the two can never disagree;
- *backward* inference (fill a layer's parameter shapes from its data
  shape + declarative kwargs) is a small per-op handler table below,
  covering the layer ops whose parameters Gluon/Module auto-materialize.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..base import MXNetError

# handler(in_shapes: List[Optional[tuple]], kwargs) mutates in_shapes,
# filling entries it can deduce.  Slot order = op positional order.
PARAM_INFER = {}


def _infer_for(*names):
    def deco(fn):
        for n in names:
            PARAM_INFER[n] = fn
        return fn
    return deco


@_infer_for("FullyConnected")
def _fc(shapes, kw):
    data = shapes[0]
    nh = int(kw.get("num_hidden", 0))
    if data is not None and nh:
        k = int(np.prod(data[1:])) if kw.get("flatten", True) and \
            len(data) > 2 else data[-1]
        if shapes[1] is None:
            shapes[1] = (nh, int(k))
        if len(shapes) > 2 and shapes[2] is None:
            shapes[2] = (nh,)


@_infer_for("Convolution")
def _conv(shapes, kw):
    data = shapes[0]
    nf = int(kw.get("num_filter", 0))
    kernel = tuple(kw.get("kernel", ()))
    groups = int(kw.get("num_group", 1))
    if data is not None and nf and kernel:
        if shapes[1] is None:
            shapes[1] = (nf, data[1] // groups) + kernel
        if len(shapes) > 2 and shapes[2] is None:
            shapes[2] = (nf,)


@_infer_for("Deconvolution")
def _deconv(shapes, kw):
    data = shapes[0]
    nf = int(kw.get("num_filter", 0))
    kernel = tuple(kw.get("kernel", ()))
    groups = int(kw.get("num_group", 1))
    if data is not None and nf and kernel:
        if shapes[1] is None:
            shapes[1] = (data[1], nf // groups) + kernel
        if len(shapes) > 2 and shapes[2] is None:
            shapes[2] = (nf,)


@_infer_for("BatchNorm", "batch_norm")
def _bn(shapes, kw):
    data = shapes[0]
    if data is not None:
        c = (data[int(kw.get("axis", 1))],)
        for i in range(1, 5):
            if shapes[i] is None:
                shapes[i] = c


@_infer_for("LayerNorm", "layer_norm")
def _ln(shapes, kw):
    data = shapes[0]
    if data is not None:
        c = (data[int(kw.get("axis", -1))],)
        for i in (1, 2):
            if shapes[i] is None:
                shapes[i] = c


@_infer_for("InstanceNorm", "GroupNorm")
def _in(shapes, kw):
    data = shapes[0]
    if data is not None:
        c = (data[1],)
        for i in (1, 2):
            if shapes[i] is None:
                shapes[i] = c


@_infer_for("Embedding")
def _embed(shapes, kw):
    if shapes[1] is None and kw.get("input_dim") and kw.get("output_dim"):
        shapes[1] = (int(kw["input_dim"]), int(kw["output_dim"]))


def _eval_op_shapes(node, in_structs):
    """Forward inference: abstract-eval the op's real body."""
    import functools
    import jax
    fn = node.op.fn
    if node.kwargs:
        fn = functools.partial(fn, **node.kwargs)
    out = jax.eval_shape(fn, *in_structs)
    return tuple(out) if isinstance(out, tuple) else (out,)


def infer_shape_graph(symbol, known: Dict[str, tuple], dtypes=None):
    """Run inference over the whole graph.

    Returns (var_shapes: dict name->shape-or-None,
             out_shapes: list shape-or-None).
    """
    import jax
    import jax.numpy as jnp
    dtypes = dtypes or {}
    nodes = symbol._topo()
    # per-node tuple of ShapeDtypeStruct-or-None
    vals: Dict[int, tuple] = {}
    var_shapes: Dict[str, Optional[tuple]] = {}

    def struct(shape, name=None):
        dt = dtypes.get(name, jnp.float32) if name else jnp.float32
        return jax.ShapeDtypeStruct(tuple(int(s) for s in shape), dt)

    for node in nodes:
        if node.is_variable:
            shape = known.get(node.name)
            if shape is None and node.attrs.get("__shape__"):
                import ast
                try:
                    declared = ast.literal_eval(node.attrs["__shape__"])
                except (ValueError, SyntaxError):
                    declared = None
                if declared is not None and all(
                        isinstance(s, int) and s > 0 for s in declared):
                    shape = tuple(declared)
            var_shapes[node.name] = tuple(shape) if shape is not None \
                else None
            vals[id(node)] = (struct(shape, node.name),) \
                if shape is not None else (None,)
            continue
        in_entries = [vals[id(n)][i] for n, i in node.inputs]
        in_shapes = [None if e is None else tuple(e.shape)
                     for e in in_entries]
        if any(s is None for s in in_shapes):
            handler = PARAM_INFER.get(node.op.name)
            if handler is not None:
                handler(in_shapes, node.kwargs)
                # write deduced shapes back onto unknown *variable* inputs
                for (src, oi), old, new in zip(node.inputs, in_entries,
                                               in_shapes):
                    if old is None and new is not None and src.is_variable:
                        var_shapes[src.name] = tuple(new)
                        vals[id(src)] = (struct(new, src.name),)
        in_entries = [vals[id(n)][i] for n, i in node.inputs]
        if any(e is None for e in in_entries):
            vals[id(node)] = (None,) * node.num_outputs
            continue
        try:
            outs = _eval_op_shapes(node, in_entries)
        except Exception as e:
            raise MXNetError(
                f"infer_shape: op {node.op.name!r} (node {node.name!r}) "
                f"failed on input shapes "
                f"{[tuple(x.shape) for x in in_entries]}: {e}") from e
        vals[id(node)] = outs

    out_shapes = []
    for n, i in symbol._outputs:
        e = vals[id(n)][i]
        out_shapes.append(None if e is None else tuple(e.shape))
    return var_shapes, out_shapes


# --------------------------------------------------------------------- dtype
# dtype overrides for ops whose output dtype is not result_type(inputs)
_DTYPE_RULES = {
    "Cast": lambda kw, ins: np.dtype(kw.get("dtype", "float32")),
    "cast": lambda kw, ins: np.dtype(kw.get("dtype", "float32")),
    "amp_cast": lambda kw, ins: np.dtype(kw.get("dtype", "float32")),
    "Embedding": lambda kw, ins: ins[1],      # weight dtype
    "one_hot": lambda kw, ins: np.dtype(kw.get("dtype", "float32")),
    "argmax": lambda kw, ins: np.dtype("float32"),   # reference semantics
    "argmin": lambda kw, ins: np.dtype("float32"),
    "topk": lambda kw, ins: np.dtype(kw.get("dtype", "float32")),
}


def infer_type_graph(symbol, known: Dict[str, object]):
    """Propagate dtypes forward (reference FInferType pass).

    Unknown variables default to float32 like the reference; op outputs
    follow numpy promotion unless overridden in _DTYPE_RULES.
    """
    nodes = symbol._topo()
    vals: Dict[int, tuple] = {}
    var_types: Dict[str, object] = {}
    for node in nodes:
        if node.is_variable:
            dt = known.get(node.name)
            if dt is None and node.attrs.get("__dtype__"):
                try:
                    dt = np.dtype(node.attrs["__dtype__"])
                except TypeError:
                    dt = None
            dt = np.dtype(dt) if dt is not None else np.dtype("float32")
            var_types[node.name] = dt
            vals[id(node)] = (dt,) * max(1, node.num_outputs)
            continue
        ins = [vals[id(n)][i] for n, i in node.inputs]
        rule = _DTYPE_RULES.get(node.op.name)
        if rule is not None:
            dt = rule(node.kwargs, ins)
        elif "dtype" in node.kwargs:
            dt = np.dtype(node.kwargs["dtype"])
        elif ins:
            dt = np.result_type(*ins)
        else:
            dt = np.dtype("float32")
        vals[id(node)] = (dt,) * node.num_outputs
    out_types = [vals[id(n)][i] for n, i in symbol._outputs]
    return var_types, out_types
