"""Symbol: the deferred computation graph.

Reference: ``python/mxnet/symbol/symbol.py`` over nnvm Graph/Node
(``3rdparty/tvm/nnvm`` — SURVEY.md 2.1).  TPU-native redesign: the graph is
a lightweight Python DAG whose nodes name registry ops; *execution* is an
interpretation of the DAG inside a ``jax.jit`` trace, so "bind" compiles the
whole graph to one XLA program — the nnvm pass pipeline (InferShape,
PlanMemory, Gradient) is replaced by jax.eval_shape, XLA buffer assignment,
and jax.grad respectively (SURVEY.md 7.1).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..base import MXNetError

__all__ = ["Symbol", "var", "Variable", "Group", "invoke_symbolic", "load",
           "load_json"]


class _SymNode:
    """Graph node: an op application or a variable (op is None)."""

    __slots__ = ("op", "inputs", "kwargs", "name", "num_outputs", "attrs")
    _counter = [0]

    def __init__(self, op, inputs, kwargs, name=None, num_outputs=1):
        self.op = op                    # OpDef or None (variable)
        self.inputs = inputs            # list of (node, out_index)
        self.kwargs = kwargs or {}
        if name is None:
            base = op.name.lower().lstrip("_") if op else "var"
            name = f"{base}{_SymNode._counter[0]}"
            _SymNode._counter[0] += 1
        self.name = name
        self.num_outputs = num_outputs
        self.attrs: Dict[str, str] = {}

    @property
    def is_variable(self):
        return self.op is None


class Symbol:
    """One or more outputs of a graph node (reference: mxnet Symbol)."""

    def __init__(self, outputs):
        # outputs: list of (node, out_index)
        self._outputs = list(outputs)

    # -- construction ------------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            index = names.index(index)
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        return (self[i] for i in range(len(self._outputs)))

    def attr(self, key):
        return self._outputs[0][0].attrs.get(key)

    def _set_attr(self, **kwargs):
        self._outputs[0][0].attrs.update(
            {k: str(v) for k, v in kwargs.items()})

    # -- graph walking -----------------------------------------------------
    def _topo(self) -> List[_SymNode]:
        order, seen = [], set()
        stack = [n for n, _ in self._outputs]
        while stack:
            node = stack[-1]
            if id(node) in seen:
                stack.pop()
                continue
            unvisited = [n for n, _ in node.inputs if id(n) not in seen]
            if unvisited:
                stack.extend(unvisited)
            else:
                seen.add(id(node))
                order.append(node)
                stack.pop()
        return order

    def list_arguments(self) -> List[str]:
        """Variable names in topo order (reference: Symbol.list_arguments)."""
        return [n.name for n in self._topo()
                if n.is_variable and not n.attrs.get("__aux__")]

    def list_auxiliary_states(self) -> List[str]:
        return [n.name for n in self._topo()
                if n.is_variable and n.attrs.get("__aux__")]

    def list_inputs(self):
        return [n.name for n in self._topo() if n.is_variable]

    def list_outputs(self) -> List[str]:
        return [f"{n.name}_output{i}" if n.num_outputs > 1 else f"{n.name}_output"
                for n, i in self._outputs]

    def get_internals(self) -> "Symbol":
        outs = []
        for n in self._topo():
            for i in range(n.num_outputs):
                outs.append((n, i))
        return Symbol(outs)

    # -- composition -------------------------------------------------------
    def __call__(self, **kwargs):
        """Compose: substitute variables by other symbols (reference:
        Symbol.__call__/_compose).  Returns a new graph."""
        mapping = {}
        for name, sym in kwargs.items():
            if not isinstance(sym, Symbol):
                raise MXNetError("compose expects Symbols")
            mapping[name] = sym._outputs[0]
        memo = {}

        def clone(node):
            if id(node) in memo:
                return memo[id(node)]
            if node.is_variable and node.name in mapping:
                new = mapping[node.name][0]
            elif node.is_variable:
                new = node
            else:
                new_inputs = [(clone(n), i) for n, i in node.inputs]
                new = _SymNode(node.op, new_inputs, node.kwargs, node.name,
                               node.num_outputs)
                new.attrs = dict(node.attrs)
            memo[id(node)] = new
            return new

        return Symbol([(clone(n), i) for n, i in self._outputs])

    # -- evaluation helpers -------------------------------------------------
    def _interpret(self, feed: Dict[str, object], train: bool = False,
                   aux_updates: Optional[Dict[str, object]] = None):
        """Evaluate graph given raw jax arrays for variables.  Pure: usable
        under jax.jit / jax.grad (this is the executor's compiled body).

        ``train=True`` enters autograd train-mode for the evaluation so
        mode-dependent ops (Dropout, BatchNorm) trace their training branch.
        ``aux_updates``: when given (and training), stateful-op state
        transitions — BatchNorm moving-stat updates — are written into it
        keyed by the aux variable's name, mirroring the reference executor's
        in-op aux mutation in a jit-pure way.
        """
        import contextlib
        import functools
        from .. import autograd
        scope = autograd.train_mode() if train else contextlib.nullcontext()
        values: Dict[int, tuple] = {}
        with scope:
            for node in self._topo():
                if node.is_variable:
                    if node.name not in feed:
                        raise MXNetError(f"missing argument {node.name!r}")
                    values[id(node)] = (feed[node.name],)
                    continue
                args = [values[id(n)][i] for n, i in node.inputs]
                if (aux_updates is not None and train
                        and node.op.aux_update is not None):
                    res = node.op.aux_update(args, node.kwargs)
                    if res is not None:
                        outs, slot_updates = res
                        for slot, val in slot_updates.items():
                            src, _ = node.inputs[slot]
                            if src.is_variable:
                                aux_updates[src.name] = val
                        values[id(node)] = tuple(outs)
                        continue
                fn = node.op.fn
                if node.kwargs:
                    fn = functools.partial(fn, **node.kwargs)
                out = fn(*args)
                nout = node.op.n_outputs(node.kwargs)
                values[id(node)] = tuple(out) if isinstance(out, tuple) \
                    else (out,)
        return [values[id(n)][i] for n, i in self._outputs]

    def infer_shape(self, **kwargs):
        """Full shape inference (nnvm InferShape pass equivalent).

        Accepts partial input: layer parameter shapes are deduced backward
        from data shapes + op kwargs (symbol/infer.py).  Raises when the
        graph cannot be fully resolved (reference behavior); use
        ``infer_shape_partial`` for a best-effort result with None holes.
        """
        arg_shapes, out_shapes, aux_shapes = self.infer_shape_partial(
            **kwargs)
        unresolved = [n for n, s in
                      zip(self.list_arguments() +
                          self.list_auxiliary_states(),
                          list(arg_shapes) + list(aux_shapes)) if s is None]
        if unresolved or any(s is None for s in out_shapes):
            raise MXNetError(
                f"infer_shape: could not resolve shapes for {unresolved}; "
                f"provide them explicitly")
        return arg_shapes, out_shapes, aux_shapes

    def infer_shape_partial(self, **kwargs):
        """Best-effort inference; unknown entries are None (reference:
        Symbol.infer_shape_partial)."""
        from .infer import infer_shape_graph
        known = {k: tuple(v) for k, v in kwargs.items() if v is not None}
        var_shapes, out_shapes = infer_shape_graph(self, known)
        args = self.list_arguments()
        aux = self.list_auxiliary_states()
        return ([var_shapes.get(n) for n in args], out_shapes,
                [var_shapes.get(n) for n in aux])

    def infer_type(self, **kwargs):
        """Dtype propagation (nnvm InferType pass equivalent); unknown
        variables default to float32 like the reference."""
        from .infer import infer_type_graph
        var_types, out_types = infer_type_graph(self, dict(kwargs))
        args = self.list_arguments()
        aux = self.list_auxiliary_states()
        return ([var_types.get(n) for n in args], out_types,
                [var_types.get(n) for n in aux])

    def eval(self, ctx=None, **kwargs):
        from ..ndarray import NDArray
        feed = {k: v._data for k, v in kwargs.items()}
        outs = self._interpret(feed)
        return [NDArray(o) for o in outs]

    # bind/simple_bind live in executor.py (imported lazily to avoid cycle)
    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, **kwargs):
        from ..executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx)

    def simple_bind(self, ctx, grad_req="write", type_dict=None, **shapes):
        from ..executor import Executor
        from .. import ndarray as nd
        arg_shapes, _, aux_shapes = self.infer_shape(**shapes)
        args = {n: nd.zeros(s) for n, s in zip(self.list_arguments(),
                                               arg_shapes)}
        aux = {n: nd.zeros(s) for n, s in zip(self.list_auxiliary_states(),
                                              aux_shapes)}
        args_grad = None
        if grad_req != "null":
            args_grad = {n: nd.zeros(s) for n, s in
                         zip(self.list_arguments(), arg_shapes)}
        return Executor(self, ctx, args, args_grad, grad_req, aux)

    # -- serialization ------------------------------------------------------
    def optimize_for(self, backend, **kwargs):
        """Apply a registered subgraph-backend pass and return the
        rewritten Symbol (reference: Symbol.optimize_for over the
        SubgraphProperty registry — src/operator/subgraph/)."""
        from ..subgraph import optimize_symbol
        return optimize_symbol(self, backend, **kwargs)

    def tojson(self) -> str:
        """nnvm-style JSON (reference: Symbol.tojson / nnvm SaveJSON)."""
        nodes = self._topo()
        idx = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jn = {
                "op": "null" if n.is_variable else n.op.name,
                "name": n.name,
                "attrs": {k: json.dumps(v) if not isinstance(v, str) else v
                          for k, v in n.kwargs.items()},
                "inputs": [[idx[id(src)], i, 0] for src, i in n.inputs],
            }
            if n.attrs:
                # user/scope attributes (ctx_group, __shape__, ...) live
                # beside op params so AttrScope metadata survives
                # save/load_json (reference keeps both in nnvm attrs)
                jn["user_attrs"] = dict(n.attrs)
            jnodes.append(jn)
        heads = [[idx[id(n)], i, 0] for n, i in self._outputs]
        return json.dumps({"nodes": jnodes, "heads": heads,
                           "mxnet_tpu_version": 1}, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- sugar --------------------------------------------------------------
    def __add__(self, other):
        return _sym_binary("broadcast_add", "_plus_scalar", self, other)

    __radd__ = __add__

    def __sub__(self, other):
        return _sym_binary("broadcast_sub", "_minus_scalar", self, other)

    def __rsub__(self, other):
        return _sym_scalar("_rminus_scalar", self, other)

    def __mul__(self, other):
        return _sym_binary("broadcast_mul", "_mul_scalar", self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _sym_binary("broadcast_div", "_div_scalar", self, other)

    def __rtruediv__(self, other):
        return _sym_scalar("_rdiv_scalar", self, other)

    def __pow__(self, other):
        return _sym_binary("broadcast_power", "_power_scalar", self, other)

    def __neg__(self):
        from ..ops.registry import get_op
        return invoke_symbolic(get_op("negative"), (self,), {})

    def __repr__(self):
        name = self.name or "grouped"
        return f"<Symbol {name}>"


def _sym_binary(opname, scalar_opname, lhs, rhs):
    from ..ops.registry import get_op
    if isinstance(rhs, Symbol):
        return invoke_symbolic(get_op(opname), (lhs, rhs), {})
    return invoke_symbolic(get_op(scalar_opname), (lhs,),
                           {"scalar": float(rhs)})


def _sym_scalar(opname, data, scalar):
    from ..ops.registry import get_op
    return invoke_symbolic(get_op(opname), (data,), {"scalar": float(scalar)})


def invoke_symbolic(opdef, args, kwargs) -> Symbol:
    """Create a graph node for an op call over Symbols (the symbolic half of
    the shared-registry frontend)."""
    kwargs = dict(kwargs)
    name = kwargs.pop("name", None)
    flat = []
    for a in args:
        if isinstance(a, (list, tuple)):
            flat.extend(a)
        else:
            flat.append(a)
    inputs = []
    for a in flat:
        if isinstance(a, Symbol):
            if len(a._outputs) != 1:
                raise MXNetError("cannot use a grouped symbol as op input")
            inputs.append(a._outputs[0])
        else:
            raise MXNetError(
                f"symbolic op {opdef.name}: all inputs must be Symbols, "
                f"got {type(a)}")
    nout = opdef.n_outputs(kwargs)
    node = _SymNode(opdef, inputs, kwargs, name, nout)
    from ..attribute import current_attrs
    scope = current_attrs()
    if scope:
        node.attrs.update(scope)
    return Symbol([(node, i) for i in range(nout)])


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None,
        init=None, stype=None, **kwargs) -> Symbol:
    """Create a variable symbol (reference: mx.sym.var / Variable)."""
    node = _SymNode(None, [], {}, name)
    from ..attribute import current_attrs
    node.attrs.update(current_attrs())
    if attr:
        node.attrs.update({k: str(v) for k, v in attr.items()})
    if shape is not None:
        node.attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        node.attrs["__dtype__"] = str(dtype)
    return Symbol([(node, 0)])


Variable = var


def zeros(shape, dtype="float32", **kwargs):
    from ..ops.registry import get_op
    return invoke_symbolic(get_op("_zeros"),
                           (), {"shape": tuple(shape), "dtype": dtype})


def ones(shape, dtype="float32", **kwargs):
    from ..ops.registry import get_op
    return invoke_symbolic(get_op("_ones"),
                           (), {"shape": tuple(shape), "dtype": dtype})


def Group(symbols: Sequence[Symbol]) -> Symbol:
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load_json(json_str: str) -> Symbol:
    """Rebuild a Symbol from nnvm-style JSON (reference: sym.load_json)."""
    from ..ops.registry import get_op
    data = json.loads(json_str)
    nodes = []
    for jn in data["nodes"]:
        if jn["op"] == "null":
            node = _SymNode(None, [], {}, jn["name"])
        else:
            opdef = get_op(jn["op"])
            kwargs = {}
            for k, v in jn.get("attrs", {}).items():
                try:
                    kwargs[k] = json.loads(v)
                except (json.JSONDecodeError, TypeError):
                    kwargs[k] = v
            inputs = [(nodes[i], oi) for i, oi, _ in jn["inputs"]]
            node = _SymNode(opdef, inputs, kwargs, jn["name"],
                            opdef.n_outputs(kwargs))
        if jn.get("user_attrs"):
            node.attrs.update(jn["user_attrs"])
        nodes.append(node)
    heads = [(nodes[i], oi) for i, oi, _ in data["heads"]]
    return Symbol(heads)


def load(fname) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())
