"""Legacy ``.params`` binary format (best-effort migration shim).

Reference surface: ``MXNDArraySave/MXNDArrayLoad`` (src/c_api/c_api.cc →
src/ndarray/ndarray.cc ``NDArray::Save/Load``) — the dmlc-stream binary
container behind ``mx.nd.save/load`` and every ``model-0000.params``
checkpoint.  Layout implemented here (dense tensors, the overwhelmingly
common case):

    uint64  kMXAPINDArrayListMagic = 0x112
    uint64  reserved = 0
    uint64  n_arrays
    per array (NDArray::Save, V2):
        uint32  NDARRAY_V2_MAGIC = 0xF993FAC9
        int32   storage_type     (0 = kDefaultStorage; sparse rejected)
        uint32  ndim             (TShape::Save)
        int64   dims[ndim]
        int32   dev_type, int32 dev_id   (Context; ignored on load)
        int32   type_flag        (mshadow order, _MSHADOW_DTYPES below)
        raw     data bytes (C-order, prod(dims) * itemsize)
    uint64  n_names
    per name: uint64 len, bytes (utf-8)

Verified by construction against the documented upstream layout; the
reference mount is empty this build, so cross-loading real upstream files
is best-effort — the round-trip through this module is exact, and the
magics/field order follow the published format.  ``nd.load`` auto-detects
the 0x112 magic and routes here; NPZ remains the native container.
"""
from __future__ import annotations

import struct

import numpy as np

from .base import MXNetError

__all__ = ["save_params_dmlc", "load_params_dmlc", "is_dmlc_params"]

_LIST_MAGIC = 0x112
_NDARRAY_V2_MAGIC = 0xF993FAC9

# mshadow type_flag order (mshadow/base.h)
_MSHADOW_DTYPES = ["float32", "float64", "float16", "uint8", "int32",
                   "int8", "int64", "bool", "int16", "uint16", "uint32",
                   "uint64", "bfloat16"]


def is_dmlc_params(path) -> bool:
    if not isinstance(path, (str, bytes)) and not hasattr(path,
                                                          "__fspath__"):
        return False                # file-like objects go to np.load
    try:
        with open(path, "rb") as f:
            head = f.read(8)
        return len(head) == 8 and \
            struct.unpack("<Q", head)[0] == _LIST_MAGIC
    except OSError:
        return False


def save_params_dmlc(path, arrays):
    """Write a name->NDArray dict in the legacy .params layout."""
    if not isinstance(arrays, dict):
        raise MXNetError("save_params_dmlc expects a dict of name->array")
    names = list(arrays.keys())
    with open(path, "wb") as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(names)))
        for name in names:
            a = arrays[name]
            npa = a.asnumpy() if hasattr(a, "asnumpy") else np.asarray(a)
            if str(npa.dtype) == "bfloat16":
                type_flag = _MSHADOW_DTYPES.index("bfloat16")
                raw = npa.view(np.uint16).tobytes()
            else:
                if str(npa.dtype) not in _MSHADOW_DTYPES:
                    npa = npa.astype(np.float32)
                type_flag = _MSHADOW_DTYPES.index(str(npa.dtype))
                raw = np.ascontiguousarray(npa).tobytes()
            f.write(struct.pack("<Ii", _NDARRAY_V2_MAGIC, 0))
            f.write(struct.pack("<I", npa.ndim))
            f.write(struct.pack(f"<{npa.ndim}q", *npa.shape))
            f.write(struct.pack("<ii", 1, 0))          # cpu(0)
            f.write(struct.pack("<i", type_flag))
            f.write(raw)
        f.write(struct.pack("<Q", len(names)))
        for name in names:
            b = name.encode("utf-8")
            f.write(struct.pack("<Q", len(b)) + b)
    return path


def load_params_dmlc(path):
    """Read a legacy .params file → dict name->NDArray (or a list when
    the file carries no names, matching mx.nd.load)."""
    from . import ndarray as nd
    with open(path, "rb") as f:
        data = f.read()
    pos = 0

    def take(fmt):
        nonlocal pos
        size = struct.calcsize(fmt)
        vals = struct.unpack_from(fmt, data, pos)
        pos += size
        return vals if len(vals) > 1 else vals[0]

    magic = take("<Q")
    if magic != _LIST_MAGIC:
        raise MXNetError(f"{path!r}: not a .params file (magic {magic:#x})")
    take("<Q")                                   # reserved
    n = take("<Q")
    arrays = []
    for _ in range(n):
        amagic = take("<I")
        if amagic != _NDARRAY_V2_MAGIC:
            raise MXNetError(
                f"{path!r}: unsupported NDArray magic {amagic:#x} "
                f"(only the dense V2 layout is implemented)")
        stype = take("<i")
        if stype != 0:
            raise MXNetError(f"{path!r}: sparse storage type {stype} "
                             f"unsupported in the .params shim")
        ndim = take("<I")
        shape = tuple(take(f"<{ndim}q")) if ndim > 1 else \
            ((take("<q"),) if ndim == 1 else ())
        take("<ii")                              # context, ignored
        type_flag = take("<i")
        if not 0 <= type_flag < len(_MSHADOW_DTYPES):
            raise MXNetError(f"{path!r}: unknown dtype flag {type_flag}")
        dtype = _MSHADOW_DTYPES[type_flag]
        count = int(np.prod(shape)) if shape else 1
        if dtype == "bfloat16":
            import jax.numpy as jnp
            raw = np.frombuffer(data, np.uint16, count, pos)
            pos += raw.nbytes
            arrays.append(nd.NDArray(
                jnp.asarray(raw).view(jnp.bfloat16).reshape(shape)))
        else:
            raw = np.frombuffer(data, np.dtype(dtype), count, pos)
            pos += raw.nbytes
            arrays.append(nd.array(raw.reshape(shape).copy()))
    n_names = take("<Q")
    names = []
    for _ in range(n_names):
        ln = take("<Q")
        names.append(data[pos:pos + ln].decode("utf-8"))
        pos += ln
    if not names:
        return arrays
    if len(names) != len(arrays):
        raise MXNetError(f"{path!r}: {len(names)} names for "
                         f"{len(arrays)} arrays")
    return dict(zip(names, arrays))
