"""``mx.npx``: NumPy-extension namespace — operators that have no NumPy
equivalent (neural-network layers, device placement, framework I/O).

Reference: ``python/mxnet/ndarray/numpy_extension/`` + ``mxnet/util.py``
set_np machinery (SURVEY.md 2.2).  The np/npx pair lets numpy-idiomatic
user code train networks: ``mx.np`` for math, ``mx.npx`` for layers.

TPU-native note: set_np()/reset_np() only flip a flag here — mx.np arrays
and mx.nd arrays are the *same* jax-backed NDArray, so there is no global
array-type switch to perform (the reference needed one because its two
array types had different C++ paths).
"""
from __future__ import annotations

import threading

from ..base import MXNetError
from ..ndarray import NDArray
from .. import ndarray as _nd

__all__ = ["set_np", "reset_np", "is_np_array", "is_np_shape",
           "save", "load", "seed",
           "relu", "sigmoid", "softmax", "log_softmax", "activation",
           "fully_connected", "convolution", "pooling", "batch_norm",
           "layer_norm", "embedding", "dropout", "one_hot", "pick",
           "topk", "rnn", "gamma", "reshape_like", "batch_dot",
           "broadcast_like", "arange_like", "sequence_mask", "waitall",
           "current_device", "num_gpus"]

_flags = threading.local()


def set_np(shape=True, array=True, dtype=False):
    """Enable numpy semantics globally (reference: mx.npx.set_np).
    A flag only: numpy semantics are always on in this build."""
    _flags.np_shape = shape
    _flags.np_array = array


def reset_np():
    _flags.np_shape = False
    _flags.np_array = False


def is_np_array():
    return getattr(_flags, "np_array", False)


def is_np_shape():
    return getattr(_flags, "np_shape", False)


def seed(s):
    from .. import random as mxrand
    mxrand.seed(s)


def waitall():
    from .. import engine
    engine.waitall()


def current_device():
    from ..context import current_context
    return current_context()


def num_gpus():
    from ..context import num_gpus as _n
    return _n()


def save(file, arr):
    """reference: npx.save — dict or list of arrays to file."""
    if isinstance(arr, NDArray):
        arr = [arr]
    _nd.save(file, arr)


def load(file):
    return _nd.load(file)


# ---------------------------------------------------------------------------
# Neural-network extension ops: thin delegations to the shared op registry
# (same FCompute bodies as mx.nd/mx.sym — one registry, three namespaces).
# ---------------------------------------------------------------------------

def _op(name, *args, **kwargs):
    return _nd.invoke_by_name(name, list(args), kwargs)


def relu(data):
    return _op("relu", data)


def sigmoid(data):
    return _op("sigmoid", data)


def activation(data, act_type="relu"):
    return _op("Activation", data, act_type=act_type)


def softmax(data, axis=-1, length=None, temperature=None):
    kwargs = {"axis": axis}
    if temperature is not None:
        kwargs["temperature"] = temperature
    return _op("softmax", data, **kwargs)


def log_softmax(data, axis=-1):
    return _op("log_softmax", data, axis=axis)


def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    if num_hidden is None:
        num_hidden = weight.shape[0]
    args = (x, weight) if bias is None else (x, weight, bias)
    return _op("FullyConnected", *args, num_hidden=num_hidden,
               no_bias=bias is None or no_bias, flatten=flatten)


def convolution(data=None, weight=None, bias=None, kernel=None, stride=None,
                dilate=None, pad=None, num_filter=None, num_group=1,
                no_bias=False, layout=None):
    args = (data, weight) if bias is None else (data, weight, bias)
    return _op("Convolution", *args, kernel=tuple(kernel),
               stride=tuple(stride or ()), dilate=tuple(dilate or ()),
               pad=tuple(pad or ()), num_filter=num_filter,
               num_group=num_group, no_bias=bias is None or no_bias,
               layout=layout)


def pooling(data, kernel=(2, 2), stride=None, pad=None, pool_type="max",
            global_pool=False):
    return _op("Pooling", data, kernel=tuple(kernel),
               stride=tuple(stride or ()), pad=tuple(pad or ()),
               pool_type=pool_type, global_pool=global_pool)


def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-3,
               momentum=0.9, fix_gamma=False, use_global_stats=False,
               output_mean_var=False, axis=1):
    return _op("BatchNorm", x, gamma, beta, running_mean, running_var,
               eps=eps, momentum=momentum, fix_gamma=fix_gamma,
               use_global_stats=use_global_stats,
               output_mean_var=output_mean_var, axis=axis)


def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    return _op("LayerNorm", data, gamma, beta, axis=axis, eps=eps)


def embedding(data, weight, input_dim=None, output_dim=None,
              dtype="float32", sparse_grad=False):
    if input_dim is None:
        input_dim, output_dim = weight.shape
    return _op("Embedding", data, weight, input_dim=input_dim,
               output_dim=output_dim, dtype=dtype,
               sparse_grad=sparse_grad)


def dropout(data, p=0.5, axes=(), mode="training"):
    return _op("Dropout", data, p=p, axes=axes, mode=mode)


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    return _op("one_hot", data, depth=depth, on_value=on_value,
               off_value=off_value, dtype=dtype)


def pick(data, index, axis=-1, mode="clip", keepdims=False):
    return _op("pick", data, index, axis=axis, mode=mode,
               keepdims=keepdims)


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False,
         dtype="float32"):
    return _op("topk", data, axis=axis, k=k, ret_typ=ret_typ,
               is_ascend=is_ascend, dtype=dtype)


def rnn(data, parameters, state, state_cell=None, state_size=None,
        num_layers=1, mode="lstm", bidirectional=False, p=0.0,
        state_outputs=True):
    args = [data, parameters, state]
    if mode == "lstm":
        args.append(state_cell)
    return _op("RNN", *args, state_size=state_size, num_layers=num_layers,
               mode=mode, bidirectional=bidirectional, p=p,
               state_outputs=state_outputs)


def gamma(data):
    return _op("gamma", data)


def reshape_like(lhs, rhs):
    return _op("reshape_like", lhs, rhs)


def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    return _op("batch_dot", lhs, rhs, transpose_a=transpose_a,
               transpose_b=transpose_b)


def broadcast_like(lhs, rhs):
    return _op("broadcast_like", lhs, rhs)


def arange_like(data, start=0.0, step=1.0, axis=None):
    return _op("arange_like", data, start=start, step=step, axis=axis)


def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    args = (data,) if sequence_length is None \
        else (data, sequence_length)
    return _op("SequenceMask", *args,
               use_sequence_length=sequence_length is not None
               or use_sequence_length, value=value, axis=axis)
