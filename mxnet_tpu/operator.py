"""Python custom operators.

Reference surface: ``python/mxnet/operator.py`` + the C++ trampoline
``src/operator/custom/custom.cc`` — ``CustomOp`` (forward/backward in
python over NDArrays), ``CustomOpProp`` (shape/type inference + operator
factory), ``mx.operator.register``, invoked as
``mx.nd.Custom(*args, op_type="name")`` / ``mx.sym.Custom(...)``.

TPU-native redesign: the reference trampolines from the C++ engine back
into python on a dedicated thread.  Here the python body runs through
``jax.pure_callback`` with a ``jax.custom_vjp`` wired to the user's
``backward`` — which means Custom ops work not only eagerly but also
inside ``hybridize()``/``jit`` traces (the callback escapes to host mid-
program), something the reference's CachedOp never supported for
CustomOp.  The host round trip makes Custom ops slow by construction —
the docstring contract mirrors the reference: use them for research
glue, not hot-path kernels.
"""
from __future__ import annotations

from typing import Dict, List, Type

import numpy as np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]


class CustomOp:
    """Base class for custom operator implementations (reference:
    mx.operator.CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise MXNetError(
            f"{type(self).__name__}.backward not implemented; gradients "
            f"through this Custom op are unavailable")

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the grad req (reference:
        CustomOp.assign)."""
        if req in ("null", 0):
            return
        if req in ("add", 3):
            dst += src
        else:                              # write / inplace
            dst[:] = src


class CustomOpProp:
    """Shape/type inference + factory (reference: mx.operator.CustomOpProp).

    Subclasses override list_arguments/list_outputs/infer_shape/
    infer_type/create_operator.
    """

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = bool(need_top_grad)
        self.kwargs: Dict[str, str] = {}

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


_CUSTOM_REGISTRY: Dict[str, Type[CustomOpProp]] = {}


def register(reg_name):
    """Register a CustomOpProp subclass under ``op_type`` (reference:
    mx.operator.register)."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        return prop_cls

    return deco


def get_all_registered():
    return dict(_CUSTOM_REGISTRY)


def _make_prop(op_type, kwargs):
    cls = _CUSTOM_REGISTRY.get(op_type)
    if cls is None:
        raise MXNetError(
            f"Custom op_type {op_type!r} is not registered "
            f"(known: {sorted(_CUSTOM_REGISTRY)})")
    # the reference passes ctor kwargs as strings through the C ABI
    prop = cls(**{k: str(v) for k, v in kwargs.items()})
    prop.kwargs = dict(kwargs)
    return prop


class _Plan:
    """Resolved shapes/dtypes + operator instance for one Custom call."""

    def __init__(self, op_type, kwargs, in_shapes, in_dtypes):
        import jax
        self.prop = _make_prop(op_type, kwargs)
        if self.prop.list_auxiliary_states():
            raise MXNetError("Custom ops with auxiliary states are not "
                             "supported on the TPU build")
        self.n_in = len(self.prop.list_arguments())
        if len(in_shapes) != self.n_in:
            raise MXNetError(
                f"Custom[{op_type}] expects {self.n_in} inputs "
                f"({self.prop.list_arguments()}), got {len(in_shapes)}")
        self.in_shapes = in_shapes
        self.in_dtypes = in_dtypes
        _, out_shapes, _ = self.prop.infer_shape(in_shapes)
        _, out_dtypes, _ = self.prop.infer_type(in_dtypes)
        self.out_specs = [jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
                          for s, d in zip(out_shapes, out_dtypes)]
        self.in_specs = [jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
                         for s, d in zip(in_shapes, in_dtypes)]
        self.op = self.prop.create_operator(None, in_shapes, in_dtypes)

    def fwd_host(self, *arrays):
        import jax.numpy as jnp
        from . import autograd
        from .ndarray import NDArray
        ins = [NDArray(jnp.asarray(np.asarray(a))) for a in arrays]
        outs = [NDArray(jnp.zeros(s.shape, s.dtype))
                for s in self.out_specs]
        self.op.forward(autograd.is_training(), ["write"] * len(outs),
                        ins, outs, [])
        return tuple(np.asarray(o._data, dtype=sp.dtype)
                     for o, sp in zip(outs, self.out_specs))

    def bwd_host(self, *arrays):
        import jax.numpy as jnp
        from .ndarray import NDArray
        n_out = len(self.out_specs)
        ograds = [NDArray(jnp.asarray(np.asarray(a)))
                  for a in arrays[:n_out]]
        rest = arrays[n_out:]
        ins = [NDArray(jnp.asarray(np.asarray(a)))
               for a in rest[:self.n_in]]
        outs = [NDArray(jnp.asarray(np.asarray(a)))
                for a in rest[self.n_in:]]
        igrads = [NDArray(jnp.zeros(s.shape, s.dtype))
                  for s in self.in_specs]
        self.op.backward(["write"] * self.n_in, ograds, ins, outs,
                         igrads, [])
        return tuple(np.asarray(g._data, dtype=s.dtype)
                     for g, s in zip(igrads, self.in_specs))


def _custom_traced(inputs, op_type, kwargs):
    """Traced (hybridize/jit) body: pure-callback forward with a
    custom_vjp backward.  Needs a callback-capable backend (CPU mesh is;
    some remote-dispatch TPU backends are not — eager Custom always
    works because it bypasses tracing entirely)."""
    import jax
    import jax.numpy as jnp

    plan = _Plan(op_type, kwargs,
                 [list(a.shape) for a in inputs],
                 [str(a.dtype) for a in inputs])

    if not any(isinstance(a, jax.core.Tracer) for a in inputs):
        # concrete arrays (Symbol.eval interpret path): run the
        # trampoline directly — callback machinery may be unsupported
        # on the backend and is unnecessary without a trace
        outs = tuple(jnp.asarray(o) for o in plan.fwd_host(*inputs))
        return outs if len(plan.out_specs) > 1 else outs[0]

    @jax.custom_vjp
    def run(*arrays):
        return jax.pure_callback(plan.fwd_host, tuple(plan.out_specs),
                                 *arrays)

    def run_fwd(*arrays):
        outs = jax.pure_callback(plan.fwd_host, tuple(plan.out_specs),
                                 *arrays)
        return outs, (arrays, outs)

    def run_bwd(res, cots):
        arrays, outs = res
        if not isinstance(cots, tuple):
            cots = (cots,)
        grads = jax.pure_callback(plan.bwd_host, tuple(plan.in_specs),
                                  *cots, *arrays, *outs)
        return tuple(grads)

    run.defvjp(run_fwd, run_bwd)
    result = run(*inputs)
    return result if len(plan.out_specs) > 1 else result[0]


def _custom_eager(nd_inputs, op_type, kwargs):
    """Eager path: direct python trampoline, no jax tracing anywhere —
    the tape node gets a host-side custom backward (reference:
    custom.cc pushes the python callbacks onto the engine)."""
    import jax.numpy as jnp
    from . import autograd
    from .ndarray import NDArray

    plan = _Plan(op_type, kwargs,
                 [list(a.shape) for a in nd_inputs],
                 [str(a._data.dtype) for a in nd_inputs])
    raw_outs = plan.fwd_host(*[a._data for a in nd_inputs])
    outs = [NDArray(jnp.asarray(o)) for o in raw_outs]

    if autograd.is_recording():
        def custom_backward(out_grads, in_primals, _plan=plan,
                            _raw_outs=raw_outs):
            grads = _plan.bwd_host(*out_grads, *in_primals, *_raw_outs)
            return tuple(jnp.asarray(g) for g in grads)

        autograd.record_custom_node(nd_inputs, outs, custom_backward,
                                    name=f"Custom[{op_type}]")
    from .engine import engine, is_naive
    eng = engine()
    if is_naive():
        for o in outs:
            o.wait_to_read()
    for o in outs:
        eng.track(o)
    return outs[0] if len(outs) == 1 else outs


def _register_custom_op():
    """Hook the 'Custom' operator into the shared registry so it is
    reachable as mx.nd.Custom / mx.sym.Custom (reference: custom.cc
    NNVM registration)."""
    from .ops.registry import register as reg_op

    def n_outputs(kwargs):
        try:
            prop = _make_prop(kwargs.get("op_type", ""),
                              {k: v for k, v in kwargs.items()
                               if k != "op_type"})
            return len(prop.list_outputs())
        except MXNetError:
            return 1

    @reg_op("Custom", num_inputs=None, num_outputs=n_outputs)
    def Custom(*data, op_type: str = "", **kwargs):
        # reached with raw arrays only under a trace (hybridize / the
        # symbolic executor's jit); the NDArray frontend below routes
        # eager calls around invoke entirely
        return _custom_traced(list(data), op_type, kwargs)

    # this module imports after the nd/sym namespaces generated their
    # frontends, so attach Custom's frontend explicitly.  The nd frontend
    # dispatches eager NDArray calls to the python trampoline (no jax
    # trace -> works on every backend); Symbols go through the registry.
    from .ops.registry import get_op, make_frontend
    from . import ndarray as nd_mod
    from . import symbol as sym_mod
    from .symbol import Symbol
    sym_frontend = make_frontend(get_op("Custom"))

    def frontend(*args, op_type: str = "", out=None, **kwargs):
        import jax
        from .ops.registry import invoke
        if args and isinstance(args[0], (list, tuple)):
            args = tuple(args[0]) + tuple(args[1:])
        if args and isinstance(args[0], Symbol):
            return sym_frontend(*args, op_type=op_type, **kwargs)
        if any(isinstance(a._data, jax.core.Tracer) for a in args):
            # inside a hybridize/jit trace: take the pure_callback path
            return invoke(get_op("Custom"), list(args),
                          {"op_type": op_type, **kwargs}, out=out)
        res = _custom_eager(list(args), op_type, kwargs)
        if out is not None:
            dsts = [out] if not isinstance(out, (list, tuple)) else list(out)
            srcs = [res] if not isinstance(res, (list, tuple)) else list(res)
            for d, s in zip(dsts, srcs):
                d._set_data(s._data)
                d._autograd_node = s._autograd_node
            return out
        return res

    for mod in (nd_mod, nd_mod.op, sym_mod, sym_mod.op):
        setattr(mod, "Custom", sym_frontend if mod in (sym_mod, sym_mod.op)
                else frontend)
    return Custom


_register_custom_op()
