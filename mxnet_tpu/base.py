"""Foundation utilities: errors, registries, environment knobs.

TPU-native re-design of the roles played by ``dmlc-core`` in the reference
(``3rdparty/dmlc-core`` -> ``dmlc::Registry``, ``dmlc::GetEnv``, ``LOG/CHECK``)
and ``python/mxnet/base.py`` (error marshalling).  There is no C ABI boundary
for Python-level errors here -- exceptions propagate natively -- but the
public surface (``MXNetError``, registries, env-var config) matches the
reference semantics.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "MXNetError",
    "NotImplementedForSymbol",
    "Registry",
    "declare_deterministic",
    "entropy_rng",
    "get_env",
    "env_truthy",
    "list_deterministic",
    "string_types",
    "numeric_types",
    "integer_types",
]

logging.basicConfig()
_LOGGER = logging.getLogger("mxnet_tpu")

string_types = (str,)
numeric_types = (float, int)
integer_types = (int,)


class MXNetError(RuntimeError):
    """Default error type raised by the framework.

    Mirrors ``mxnet.base.MXNetError`` (reference: python/mxnet/base.py).
    In the reference this wraps errors marshalled across the C ABI via
    ``MXGetLastError``; here it is raised directly.
    """


class NotImplementedForSymbol(MXNetError):
    """Raised when an NDArray-only operation is attempted on a Symbol."""

    def __init__(self, function, alias=None, *args):
        super().__init__()
        self.function = function.__name__ if callable(function) else str(function)
        self.alias = alias
        self.args_ = [str(type(a)) for a in args]

    def __str__(self):
        msg = f"Function {self.function}"
        if self.alias:
            msg += f" (alias {self.alias})"
        if self.args_:
            msg += " with arguments (" + ",".join(self.args_) + ")"
        msg += " is not supported for Symbol and only available in NDArray."
        return msg


class Registry:
    """Generic name -> object registry.

    TPU-native equivalent of ``dmlc::Registry<T>`` (reference:
    3rdparty/dmlc-core/include/dmlc/registry.h), which backs the op registry,
    data-iterator registry, kvstore registry, etc. in the reference.
    """

    _registries: Dict[str, "Registry"] = {}

    def __init__(self, name: str):
        self.name = name
        self._entries: Dict[str, Any] = {}
        self._lock = threading.Lock()
        Registry._registries[name] = self

    @classmethod
    def get(cls, name: str) -> "Registry":
        if name not in cls._registries:
            Registry(name)
        return cls._registries[name]

    def register(self, name: str, obj: Any = None, override: bool = False):
        """Register ``obj`` under ``name``; usable as a decorator."""
        if obj is None:
            def _decorator(fn):
                self.register(name, fn, override=override)
                return fn
            return _decorator
        with self._lock:
            if name in self._entries and not override:
                raise MXNetError(
                    f"'{name}' already registered in registry '{self.name}'")
            self._entries[name] = obj
        return obj

    def find(self, name: str) -> Optional[Any]:
        return self._entries.get(name)

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def __getitem__(self, name: str) -> Any:
        if name not in self._entries:
            raise MXNetError(
                f"'{name}' is not registered in registry '{self.name}'. "
                f"Known: {sorted(self._entries)[:20]}...")
        return self._entries[name]

    def list_names(self) -> List[str]:
        return sorted(self._entries)

    def items(self):
        return self._entries.items()


# ---------------------------------------------------------------------------
# Environment knob registry.
#
# The reference scatters ~100 `dmlc::GetEnv` calls across use sites (SURVEY.md
# 5.6); here every knob is declared once so `mxnet_tpu.util.list_env_vars()`
# can document them all.
# ---------------------------------------------------------------------------
_ENV_REGISTRY: Dict[str, tuple] = {}


def declare_env(name: str, default, doc: str = ""):
    _ENV_REGISTRY[name] = (default, doc)
    return name


def list_env_vars() -> Dict[str, tuple]:
    return dict(_ENV_REGISTRY)


def get_env(name: str, default=None, typ: Callable = None):
    """Read an environment knob (equivalent of ``dmlc::GetEnv``)."""
    if name in _ENV_REGISTRY and default is None:
        default = _ENV_REGISTRY[name][0]
    raw = os.environ.get(name)
    if raw is None:
        return default
    if typ is None and default is not None:
        typ = type(default)
    if typ is bool:
        return raw not in ("0", "false", "False", "")
    return typ(raw) if typ else raw


def env_truthy(name: str, default: bool = False) -> bool:
    return get_env(name, default, bool)


# ---------------------------------------------------------------------------
# Deterministic-surface registry.
#
# Every headline guarantee this repro ships is a determinism contract:
# byte-identical trace generation/replay summaries, bit-exact
# checkpoint resume, seeded fault plans, unbiased-but-seeded stochastic
# quantization.  Each such surface is declared ONCE here (pure strings
# — zero runtime coupling to the modules they name) and mxlint's
# determinism-soundness pass statically verifies that no unseeded or
# ambient entropy source (global `random` state, module-level
# `np.random` draws, wall-clock-seeded RNGs, uuid4, os.urandom,
# builtin hash() on strings, unordered set iteration) is reachable
# from any declared surface over the call graph.
# ---------------------------------------------------------------------------
_DETERMINISTIC_REGISTRY: Dict[str, str] = {}


def declare_deterministic(name: str, note: str = ""):
    """Declare ``name`` (a fully-qualified function or class path, e.g.
    ``mxnet_tpu.serving.traffic.generate_trace``; a class covers every
    method) a deterministic surface: equal inputs must yield identical
    outputs across runs.  Enforced statically by mxlint's
    determinism-soundness pass (docs/static_analysis.md §14)."""
    _DETERMINISTIC_REGISTRY[name] = note
    return name


def list_deterministic() -> Dict[str, str]:
    """{declared surface: contract note} (tools/diagnose.py reports the
    count; the mxlint pass harvests the declarations statically)."""
    return dict(_DETERMINISTIC_REGISTRY)


def entropy_rng():
    """The ONE sanctioned source of deliberate nondeterminism: a
    ``random.Random`` seeded from OS entropy.  Retry/backoff jitter
    MUST be nondeterministic (replicas retrying in lockstep re-collide
    forever), but an anonymous ``random.Random()`` at the use site is
    indistinguishable from a forgotten seed — routing through this
    helper marks the intent, and the determinism-soundness pass exempts
    exactly this function while flagging ad-hoc unseeded RNGs."""
    import random as _random
    return _random.Random(os.urandom(16))


# The contract surfaces (mxlint resolves these against the call graph;
# a name with no matching definition is simply inert, so declarations
# may precede the code they cover).
declare_deterministic(
    "mxnet_tpu.serving.traffic.generate_trace",
    "equal TraceConfigs yield byte-identical JSONL traces — one "
    "RandomState(seed) drives every draw in arrival order")
declare_deterministic(
    "mxnet_tpu.serving.traffic.replay_trace",
    "per-client backoff jitter is seeded (jitter_seed), so identical "
    "twins replaying one trace make identical retry decisions")
declare_deterministic(
    "mxnet_tpu.serving.traffic.Trace",
    "save/load round-trips bit-exact JSONL (fixed field order)")
declare_deterministic(
    "mxnet_tpu.serving.traffic.predict_payload",
    "trace rows rebuild the same payload on every replay")
declare_deterministic(
    "mxnet_tpu.serving.traffic.prompt_tokens",
    "trace rows rebuild the same prompt on every replay")
declare_deterministic(
    "mxnet_tpu.parallel.checkpoint.CheckpointManager.save",
    "bit-exact resume: what save writes, restore rebuilds")
declare_deterministic(
    "mxnet_tpu.parallel.checkpoint.CheckpointManager.restore",
    "bit-exact resume (training_resilience.md §3)")
declare_deterministic(
    "mxnet_tpu.parallel.checkpoint.save_checkpoint",
    "module-level save wrapper — same contract as CheckpointManager")
declare_deterministic(
    "mxnet_tpu.parallel.checkpoint.load_checkpoint",
    "module-level restore wrapper")
declare_deterministic(
    "mxnet_tpu.parallel.trainer.ShardedTrainer.extra_state",
    "checkpointed alongside params/opt_state; must serialize "
    "identically for identical training state")
declare_deterministic(
    "mxnet_tpu.parallel.trainer.ShardedTrainer.set_extra_state",
    "restore-side twin of extra_state")
declare_deterministic(
    "mxnet_tpu.faults.FaultPlan",
    "chaos is repeatable: per-rule RNGs are seeded from "
    "(plan seed, pattern, mode)")
declare_deterministic(
    "mxnet_tpu.quantize.quantize",
    "stochastic rounding draws from an explicit jax PRNG key — "
    "quantized parity is byte-identical given the key")
declare_deterministic(
    "mxnet_tpu.quantize.quantize_with_feedback",
    "error-feedback quantization — same key contract")
declare_deterministic(
    "mxnet_tpu.quantize.allreduce_sum",
    "quantized collective: deterministic given keys and inputs")
declare_deterministic(
    "mxnet_tpu.quantize.allreduce_mean",
    "quantized collective: deterministic given keys and inputs")
declare_deterministic(
    "benchmark.bench_traffic._run_one",
    "the frozen/scaled twins must differ ONLY in autoscaler budget — "
    "ambient entropy in the twin path voids the comparison")


# Core knobs (kept name-compatible with the reference where one exists).
declare_env("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice",
            "Execution engine: 'NaiveEngine' forces synchronous op execution "
            "(debug/bisection mode); default is async (XLA/PJRT async dispatch).")
declare_env("MXNET_SEED", None, "Global RNG seed fixed at import if set.")
declare_env("MXNET_EXEC_BULK_EXEC_TRAIN", "1",
            "Bulk-exec mode: compile the whole eager backward tape into one "
            "cached XLA program (autograd bulk replay). Set 0 to disable.")
declare_env("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", 15,
            "engine.bulk_size default when bulk-exec is on; bulk backward "
            "runs when bulk_size > 1.")
declare_env("MXNET_FLASH_BLOCK_Q", None,
            "Override the flash-attention query block size (default: "
            "per-seqlen tuned table).")
declare_env("MXNET_FLASH_BLOCK_K", None,
            "Override the flash-attention key block size.")
declare_env("MXNET_CACHED_OP_CACHE_SIZE", 16,
            "Max compiled programs kept per CachedOp (LRU-evicted beyond, "
            "with a churn warning); override per block via "
            "hybridize(cache_size=...).")
declare_env("MXNET_FUSED_HYBRID_STEP", "1",
            "Fuse a deferred single-CachedOp backward with the optimizer "
            "update into one donated program in Trainer.step "
            "(record/backward/step at fused-step cost); 0 = always eager.")
declare_env("MXNET_DEFERRED_HYBRID_FWD", "1",
            "Defer a hybridized training forward so Trainer.step can "
            "compile forward+backward+optimizer into ONE donated program "
            "(any output read before step materializes the standalone "
            "forward); 0 = always dispatch the forward eagerly.")
declare_env("MXNET_CACHED_OP_SAVE_POLICY", "dots_no_batch",
            "What the hybridized training forward saves for backward: "
            "all / dots / dots_no_batch / none (memory/recompute dial).")
declare_env("MXNET_FUSED_STEP_SAVE_POLICY", "auto",
            "Save policy INSIDE the one-program fused step: 'auto' "
            "(default) AOT-probes the save-everything variant's peak "
            "memory and uses it when it fits (reclaims the checkpoint "
            "recompute tax), else falls back to the CachedOp policy; "
            "or force all / dots / dots_no_batch / none / inherit.")
declare_env("MXNET_KVSTORE_BIGARRAY_BOUND", 1000000,
            "Arrays above this many elements get their own allreduce bucket.")
declare_env("MXNET_KVSTORE_GRAD_COMPRESSION", None,
            "Process-wide default gradient compression for every created "
            "kvstore: a CompressionSpec string — 'int8' or 'fp8', "
            "optionally with options ('int8:block=64,stochastic=1,"
            "error_feedback=0').  On the 'xla' tier quant/dequant runs "
            "inside the jitted collective (only compressed payloads "
            "cross chips; kvstore.wire.bytes vs kvstore.push.bytes is "
            "the live ratio).  Unset (default) = uncompressed; "
            "set_gradient_compression() overrides per store.")
declare_env("MXNET_PROFILER_AUTOSTART", 0, "Start profiler at import.")
declare_env("MXNET_EXCEPTION_VERBOSE", 0, "Verbose async error traces.")
declare_env("MXNET_DEFAULT_DTYPE", "float32", "Default dtype for new arrays.")
declare_env("MXNET_TPU_DISABLE_NATIVE", "0",
            "1 = skip building/loading the native C++ IO library and use "
            "the pure-python RecordIO tier.")
declare_env("MXNET_ENGINE_SANITIZE", "0",
            "1 = concurrency sanitizer: engine/serving locks record "
            "per-thread acquisition order and raise MXNetError on a "
            "cross-thread lock-order inversion (potential deadlock), "
            "in-place NDArray writes assert the array is engine-tracked, "
            "and framework threads (engine.make_thread) are registered "
            "with owner+creation site so engine.check_thread_leaks() "
            "raises on any thread surviving its owner's stop (asserted "
            "at test teardown). Debug/CI knob (sanity_lint re-runs the "
            "serving+engine tests under it); off by default, zero cost "
            "when off.")
declare_env("MXNET_TEST_CTX", "cpu",
            "Context for test_utils.default_context (the reference's "
            "GPU-suite switch): 'cpu', 'tpu', ... — any mxnet_tpu.context "
            "constructor name.")
declare_env("MXNET_TEST_PJRT_PLUGIN", None,
            "Path to a PJRT plugin .so for the framework-free StableHLO "
            "runner (tools/shlo_run.py, tests/test_shlo_runner.py); the "
            "end-to-end artifact tests only run when set.")
declare_env("MXNET_RUNTIME_METRICS", "0",
            "1 = enable the process-wide runtime metrics registry "
            "(mxnet_tpu.runtime_metrics): op dispatch counters/latency, "
            "engine/io/kvstore/trainer instrumentation, Prometheus + "
            "chrome-trace + TensorBoard exporters. Off by default; the "
            "disabled path is a single flag check per site.")
declare_env("MXNET_RUNTIME_METRICS_GRAD_NORM", "0",
            "1 = also sample the global L2 gradient norm into the "
            "trainer.grad_norm gauge after each step (forces a device "
            "sync per step to read gradients; NaN/blowup debugging aid).")
declare_env("MXNET_TRACE", "0",
            "1 = enable the request span tracer (mxnet_tpu.tracing): "
            "every serving request gets a trace-id/span-id timeline "
            "(admission, queue wait, batch assembly, execute, prefill, "
            "decode steps, eviction) exportable as chrome-trace/JSONL, "
            "with histogram exemplars linking Prometheus quantiles to "
            "traces and the flight recorder dumping recent traces on "
            "overload incidents. Off by default; the disabled path is "
            "a single flag check per site and compiles zero additional "
            "XLA programs.")
declare_env("MXNET_TRACE_SAMPLE", 1.0,
            "Head-based trace sampling rate in [0, 1]: the keep/drop "
            "decision is made once per request at root-span start "
            "(deterministic stride, so 0.25 keeps exactly every 4th "
            "trace). 1.0 = trace everything (default).")
declare_env("MXNET_TRACE_RING", 64,
            "Completed traces retained by the flight-recorder ring "
            "(mxnet_tpu.tracing) — always the most recent N; older "
            "traces are evicted in completion order.")
declare_env("MXNET_SERVING_MAX_BATCH", 8,
            "Serving: max rows coalesced into one dispatched batch "
            "(mxnet_tpu.serving.DynamicBatcher); shape buckets are "
            "powers of two up to this cap, so at most "
            "ceil(log2(max_batch))+1 programs compile per model "
            "signature.")
declare_env("MXNET_SERVING_MAX_LATENCY_US", 2000,
            "Serving: how long the batcher holds the FIRST request of a "
            "forming batch waiting for more work before dispatching a "
            "partial batch (microseconds; the latency half of the "
            "batching policy).")
declare_env("MXNET_SERVING_QUEUE_DEPTH", 128,
            "Serving: bound on total outstanding work per ModelServer "
            "(queued + dispatched-but-unfinished requests); admission "
            "sheds at it even below the queue-only shed watermark.")
declare_env("MXNET_SERVING_SHED_WATERMARK", None,
            "Serving: queue depth at/above which new requests are shed "
            "with ServerOverloadedError(retry_after_ms) instead of "
            "queued (load-shedding watermark; default: the full queue "
            "capacity MXNET_SERVING_QUEUE_DEPTH).")
declare_env("MXNET_SERVING_WORKERS", 1,
            "Serving: dispatch worker threads per ModelServer (each "
            "forms and executes whole batches; >1 overlaps host "
            "pre/post-processing with device execution).")
declare_env("MXNET_SERVING_RETRY_AFTER_MS", 50,
            "Serving: retry-after hint (milliseconds) attached to "
            "ServerOverloadedError when a request is shed.")
declare_env("MXNET_SERVING_DECODE_PAGE_SIZE", 16,
            "Decode engine: tokens per KV-cache page "
            "(mxnet_tpu.serving.kv_cache). Smaller pages waste less "
            "HBM on short sequences but deepen the per-sequence block "
            "table; the ragged-paged-attention kernel reads one page "
            "per grid step.")
declare_env("MXNET_SERVING_DECODE_POOL_PAGES", 64,
            "Decode engine: TOTAL pages preallocated in the device KV "
            "pool, including the reserved null page 0 (usable pages = "
            "pool - 1). Pool bytes = 2 * layers * pages * page_size * "
            "heads * head_dim * dtype_size.")
declare_env("MXNET_SERVING_DECODE_MAX_BATCH", 4,
            "Decode engine: sequence slots in the fixed-shape decode "
            "step (token-level continuous batching admits/evicts into "
            "these slots every step). ONE decode program compiles for "
            "this batch size regardless of traffic mix.")
declare_env("MXNET_SERVING_DECODE_MAX_NEW_TOKENS", 32,
            "Decode engine: default cap on generated tokens per "
            "request (generate(max_new_tokens=...) overrides, bounded "
            "by the model's max_context).")
declare_env("MXNET_SERVING_PREFIX_CACHE", "0",
            "Decode engine: enable copy-on-write prefix caching "
            "(docs/serving.md §9) — full prompt pages are "
            "content-addressed in a radix tree, a request whose prefix "
            "is cached aliases the shared (refcounted) KV pages and "
            "skips that prefill; the one page it appends into is "
            "copy-on-write duplicated.  Lookup failures degrade to a "
            "plain prefill.")
declare_env("MXNET_SERVING_PREFIX_CACHE_PAGES", 0,
            "Decode engine: cap on KV pages the prefix cache may hold "
            "(refcount-aware LRU evicts beyond it; cache-only pages "
            "are also evicted on demand when admission needs the free "
            "list).  0 (default) = bounded by the pool alone.")
declare_env("MXNET_SERVING_SPEC_K", 0,
            "Decode engine: speculative-decoding proposal depth — the "
            "draft model proposes up to k tokens per sequence per "
            "round and the target verifies all k+1 positions in ONE "
            "program call (greedy acceptance is exact, so outputs are "
            "byte-identical with speculation on or off).  0 (default) "
            "disables; requires a draft model "
            "(add_decoder(draft=...) or MXNET_SERVING_SPEC_DRAFT).")
declare_env("MXNET_SERVING_SPEC_DRAFT", None,
            "Decode engine: repository model name whose decode model "
            "serves as the DEFAULT speculative-decoding draft for "
            "decoder entries registered without an explicit "
            "add_decoder(draft=...).  The named entry must be "
            "registered before the first generate() call resolves it.")
declare_env("MXNET_SERVING_DEADLINE_DEFAULT", None,
            "Serving: default end-to-end deadline (seconds, float) for "
            "predict()/generate() calls that pass no timeout.  The "
            "timeout is an absolute deadline carried through admission "
            "-> queue -> batch assembly -> execute: expired requests "
            "are cancelled BEFORE consuming a batch slot and fail with "
            "DeadlineExceededError.  Unset (default) = no deadline.")
declare_env("MXNET_SERVING_RETRY_MAX", 2,
            "Serving: max re-executions of a TRANSIENT failure "
            "(exc.transient truthy, e.g. an injected execute fault) "
            "per coalesced batch / decode model call, with jittered "
            "exponential backoff.  0 disables retries.")
declare_env("MXNET_SERVING_RETRY_BACKOFF_MS", 10,
            "Serving: base of the jittered exponential retry backoff "
            "(sleep ~ backoff * 2^attempt * U[0.5,1.0) milliseconds "
            "between transient-failure retries).")
declare_env("MXNET_SERVING_CIRCUIT_WINDOW", 20,
            "Serving circuit breaker: sliding window of the last N "
            "execute outcomes per model version; the breaker can only "
            "trip once the window is full (doubling as the min-samples "
            "guard).  0 disables the breaker.")
declare_env("MXNET_SERVING_CIRCUIT_THRESHOLD", 0.5,
            "Serving circuit breaker: error rate over the full sliding "
            "window at/above which the circuit OPENs (admissions shed "
            "instantly with CircuitOpenError + retry-after until the "
            "cooldown's half-open probe).")
declare_env("MXNET_SERVING_CIRCUIT_COOLDOWN_MS", 1000,
            "Serving circuit breaker: how long an OPEN circuit sheds "
            "before admitting ONE half-open probe request (probe "
            "success re-closes, failure re-opens).")
declare_env("MXNET_SERVING_REPLICAS", 1,
            "Serving: number of replicas per model version "
            "(mxnet_tpu.serving.replica, docs/serving.md §10).  With "
            "N > 1 the server builds a ReplicaSet — N data-parallel "
            "replicas on disjoint device groups of the mesh, each with "
            "its own program cache / decode engine / KV pool — and "
            "routes least-loaded among HEALTHY replicas; a failed "
            "replica's requests fail over to siblings under their "
            "original deadlines.  1 (default) = the single-replica "
            "path, byte-identical to pre-replica behavior.")
declare_env("MXNET_SERVING_REPLICA_HEARTBEAT_MS", 50,
            "Serving replicas: heartbeat interval per replica worker "
            "(milliseconds).  Each replica's heartbeat thread beats, "
            "then sweeps the whole set for stale siblings, so a "
            "stalled replica is detected by its peers even with zero "
            "traffic.")
declare_env("MXNET_SERVING_REPLICA_HEARTBEAT_WINDOW_MS", 500,
            "Serving replicas: a replica whose last heartbeat is older "
            "than this window is marked UNHEALTHY (unroutable) until "
            "beats resume AND it re-passes prewarm (the rolling-"
            "recovery gate: a rejoining replica never serves a cold "
            "program).")
declare_env("MXNET_SERVING_REPLICA_FAILURE_THRESHOLD", 3,
            "Serving replicas: consecutive typed execute failures that "
            "trip one replica's circuit breaker (UNHEALTHY, sheds to "
            "siblings) without waiting for the sliding error-rate "
            "window to fill — the dead-replica fast path.  After "
            "MXNET_SERVING_CIRCUIT_COOLDOWN_MS one probe request may "
            "re-close it.  0 = windowed error rate only.")
declare_env("MXNET_SERVING_TENANT_TIERS", None,
            "Tiered admission (mxnet_tpu.serving.admission, "
            "docs/serving.md §11): 'name=priority[/quota_rps[/burst]]' "
            "comma-separated, e.g. 'gold=100,silver=10/20,free=1/5'. "
            "Higher priority survives overload longer (low tiers "
            "priority-shed first); quota_rps meters each tenant "
            "through a token bucket of capacity burst.  Unset "
            "(default) = admission gate off (every request rides the "
            "watermark shed alone).")
declare_env("MXNET_SERVING_ADMISSION_SHED_START", 0.5,
            "Overload pressure (0..1 — the serving queue fraction, "
            "max'd with the autoscaler's published SLO pressure) at "
            "which the LOWEST tenant tier starts shedding; tiers "
            "above it shed at evenly spaced higher thresholds and the "
            "top tier only at full pressure.")
declare_env("MXNET_SERVING_AUTOSCALE_MIN", 1,
            "Autoscaler floor on replicas per model "
            "(mxnet_tpu.serving.autoscaler, docs/serving.md §11); "
            "scale-down never drains below it.")
declare_env("MXNET_SERVING_AUTOSCALE_MAX", 4,
            "Autoscaler ceiling on replicas per model (the "
            "max-replica budget) — a sustained breach at the ceiling "
            "is counted as a 'blocked' decision, not actuated.")
declare_env("MXNET_SERVING_AUTOSCALE_INTERVAL_MS", 200,
            "Autoscaler control period: one sense -> decide -> "
            "actuate tick per interval (milliseconds).")
declare_env("MXNET_SERVING_AUTOSCALE_BREACH_TICKS", 3,
            "Scale-up hysteresis: consecutive SLO-breach ticks before "
            "adding a replica, MINUS the ticks the measured prewarm "
            "time will consume (prewarm-aware lead — capacity must "
            "start building before the window ends; floor 1).")
declare_env("MXNET_SERVING_AUTOSCALE_IDLE_TICKS", 10,
            "Scale-down hysteresis: consecutive idle ticks (queue "
            "under the low band AND latencies under the scale-down "
            "margin of their SLOs) before draining a replica.")
declare_env("MXNET_SERVING_AUTOSCALE_COOLDOWN_UP_MS", 1000,
            "Refractory period after a scale-up (or a failed "
            "actuation) before the next scale-up — one burst must not "
            "staircase the fleet to the ceiling.")
declare_env("MXNET_SERVING_AUTOSCALE_COOLDOWN_DOWN_MS", 5000,
            "Refractory period after ANY replica-count change before "
            "a scale-down — capacity just added (or a just-survived "
            "burst) must prove itself idle first.")
declare_env("MXNET_SERVING_AUTOSCALE_PREWARM_LEAD_MS", 0,
            "Initial estimate of one add_replica prewarm "
            "(milliseconds) for the prewarm-aware scale-up lead; "
            "refined at runtime by an EWMA of measured prewarms.  "
            "0 (default) = no lead until the first measured add.")
declare_env("MXNET_SERVING_AUTOSCALE_SLO_TTFT_P99_MS", None,
            "Declared SLO target: windowed p99 time-to-first-token "
            "(serving.decode.ttft.seconds) above this breaches and "
            "counts toward scale-up.  Unset (default) = TTFT not "
            "targeted.")
declare_env("MXNET_SERVING_AUTOSCALE_SLO_LATENCY_P99_MS", None,
            "Declared SLO target: windowed p99 end-to-end predict "
            "latency (serving.request.seconds) above this breaches "
            "and counts toward scale-up.  Unset (default) = latency "
            "not targeted.")
declare_env("MXNET_SERVING_AUTOSCALE_QUEUE_HIGH", None,
            "Declared SLO target: serving.queue.depth at/above this "
            "breaches (saturation shows in the queue before the "
            "latency histograms move); the scale-down band defaults "
            "to a quarter of it.  Unset (default) = queue not "
            "targeted.")
declare_env("MXNET_SERVING_TRACE_SEED", 0,
            "Workload-trace generator seed "
            "(mxnet_tpu.serving.traffic.TraceConfig): one RandomState "
            "drives every draw, so equal configs yield byte-identical "
            "JSONL traces.")
declare_env("MXNET_SERVING_TRACE_RATE", 20.0,
            "Workload-trace base arrival rate (requests/s) before the "
            "diurnal ramp and burst multipliers.")
declare_env("MXNET_SERVING_TRACE_SPEED", 1.0,
            "Trace-replay time compression "
            "(serving.traffic.replay_trace): 2.0 plays an 8s trace in "
            "4s wall time; the recorded timeline itself is unchanged.")
declare_env("MXNET_FAULTS", None,
            "Deterministic fault-injection plan for chaos testing "
            "(mxnet_tpu.faults): 'site=mode[,k=v...][;...]' with mode "
            "in fail|delay|corrupt|stall and keys p/after/times/ms/"
            "seed, e.g. 'serving.execute=fail,p=0.05,seed=7'.  Sites "
            "thread through deploy, compile_cache, the serving "
            "batcher, the decode engine, the KV page allocator, and "
            "the replica layer (replica.<rid>.{execute,heartbeat,"
            "decode.*} — kill/stall one replica by id, or every "
            "replica via the replica.* glob).  Training-plane sites: "
            "train.step, train.data.next, kvstore.push, kvstore.pull, "
            "kvstore.pushpull (the fused XLA collective), "
            "checkpoint.save (corrupt = bit-flip a saved payload), "
            "checkpoint.restore.  Unset (default) = "
            "injection off at zero cost.")
declare_env("MXNET_TRAIN_STEP_TIMEOUT_MS", 0,
            "Deadline on one ShardedTrainer.step(): the compiled step "
            "(dispatch + completion) runs on a watchdog thread and a "
            "wedged collective raises TrainStepTimeoutError instead "
            "of hanging the train loop (docs/training_resilience.md). "
            "0 (default) = no deadline, direct in-thread dispatch.")
declare_env("MXNET_TRAIN_SLOW_STEP_FACTOR", 0.0,
            "Straggler detection: a step slower than this multiple of "
            "the rolling median step time increments "
            "train.slow_steps and dumps a flight-recorder incident. "
            "0 (default) = off.")
declare_env("MXNET_TRAIN_MAX_RESTARTS", 5,
            "TrainingSupervisor crash-loop breaker: more than this "
            "many CONSECUTIVE restore+restart cycles without a "
            "completed step raises CrashLoopError instead of "
            "retrying forever (progress resets the run).")
declare_env("MXNET_TRAIN_RESTART_BACKOFF_MS", 100,
            "Base of the TrainingSupervisor's jittered exponential "
            "restart backoff (doubles per consecutive failure, "
            "jitter U[0.5, 1.0)).")
declare_env("MXNET_TRAIN_RESTART_BACKOFF_MAX_MS", 5000,
            "Cap on one TrainingSupervisor restart backoff sleep.")
declare_env("MXNET_PEAK_TFLOPS", 0.0,
            "Per-chip peak TFLOP/s used as the train.mfu denominator "
            "(perf_account.detect_peak_tflops).  0 (default) = "
            "auto-detect from the device kind (v5p 459, v5e 197, CPU "
            "0.15 bf16-peak table); set explicitly for hardware the "
            "table does not know.  bench.py's BENCH_PEAK_TFLOPS "
            "overrides this for benchmark runs.")
declare_env("MXNET_SERVING_QUANT_REQUIRE_DIGEST", "1",
            "Serving admission of quantized artifacts "
            "(ModelRepository.load_artifact): 1 (default) rejects a "
            "manifest v4 quantization block that ships without its "
            "scale digest — undetectable scale tampering/corruption — "
            "with a clear MXNetError; 0 admits unprotected scales "
            "(dev/test only).  A PRESENT digest is always verified "
            "regardless of this knob.")
declare_env("MXNET_SERVING_QUANT_MAX_REL_ERR", None,
            "Serving admission bound on a quantized artifact's "
            "recorded calibration error: reject at "
            "ModelRepository.load_artifact when the manifest's "
            "quantization.calibration.max_rel_err exceeds this float "
            "(quality gate on what a replica will serve).  Unset "
            "(default) = no bound.")
declare_env("MXNET_COMPILE_CACHE_DIR", None,
            "Persistent AOT compiled-executable cache directory "
            "(mxnet_tpu.compile_cache): serving bucket programs are "
            "content-addressed on (StableHLO hash, shape bucket, "
            "dtypes, device topology, jax version) and reloaded via "
            "PJRT executable deserialization instead of recompiling — "
            "a warm server restart compiles ZERO new XLA programs. "
            "Unset (default) = disabled.")
declare_env("MXNET_COMPILE_CACHE_MAX_BYTES", 1073741824,
            "Size bound on the compile-cache directory; least-recently-"
            "used entries are evicted beyond it (hits refresh recency). "
            "0 = unbounded.")
