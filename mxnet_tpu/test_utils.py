"""Test fixture library (reference: python/mxnet/test_utils.py —
``check_numeric_gradient``, ``check_consistency``, ``assert_almost_equal``,
``rand_ndarray``, ``default_context`` — SURVEY.md §4: "recreate this module
early; half the test suite is expressible through it")."""
from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .base import MXNetError, get_env
from .context import Context, cpu

__all__ = ["default_context", "set_default_context", "assert_almost_equal",
           "same", "almost_equal", "rand_ndarray", "rand_shape_2d",
           "rand_shape_3d", "check_numeric_gradient", "check_consistency",
           "numeric_grad", "simple_forward", "check_symbolic_forward",
           "check_symbolic_backward"]

_DEFAULT_CTX = None


def default_context() -> Context:
    """Test context; switched by MXNET_TEST_CTX like the reference's
    GPU-suite env switch (SURVEY.md §4)."""
    global _DEFAULT_CTX
    if _DEFAULT_CTX is not None:
        return _DEFAULT_CTX
    name = get_env("MXNET_TEST_CTX", "cpu")
    from . import context as ctx_mod
    return getattr(ctx_mod, name.split("(")[0])(0)


def set_default_context(ctx: Context):
    global _DEFAULT_CTX
    _DEFAULT_CTX = ctx


def _as_np(x):
    from .ndarray import NDArray
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def same(a, b) -> bool:
    return np.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20) -> bool:
    return np.allclose(_as_np(a), _as_np(b), rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-6, names=("a", "b")):
    a_np, b_np = _as_np(a), _as_np(b)
    if a_np.shape != b_np.shape:
        raise AssertionError(
            f"shape mismatch: {names[0]}{a_np.shape} vs {names[1]}{b_np.shape}")
    if not np.allclose(a_np, b_np, rtol=rtol, atol=atol):
        err = np.abs(a_np - b_np)
        rel = err / (np.abs(b_np) + atol)
        idx = np.unravel_index(np.argmax(rel), rel.shape)
        raise AssertionError(
            f"{names[0]} != {names[1]} (rtol={rtol}, atol={atol}): "
            f"max abs err {err.max():.3g}, max rel err {rel.max():.3g} "
            f"at {idx}: {a_np[idx]} vs {b_np[idx]}")


def rand_ndarray(shape, stype="default", density=None, dtype="float32",
                 ctx=None):
    from . import random as mxrand
    from .ndarray import NDArray
    import jax
    arr = jax.random.uniform(mxrand.next_key(), tuple(shape), minval=-1.0,
                             maxval=1.0)
    import jax.numpy as jnp
    return NDArray(arr.astype(jnp.dtype(dtype)), ctx=ctx)


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def simple_forward(fn, *inputs, **kwargs):
    from .ndarray import array
    outs = fn(*[array(i) for i in inputs], **kwargs)
    if isinstance(outs, (list, tuple)):
        return [o.asnumpy() for o in outs]
    return outs.asnumpy()


def numeric_grad(f: Callable[[List[np.ndarray]], float],
                 inputs: List[np.ndarray], eps: float = 1e-4):
    """Central finite differences of a scalar function (reference:
    test_utils.numeric_grad)."""
    grads = []
    for i, x in enumerate(inputs):
        g = np.zeros_like(x, dtype=np.float64)
        flat = x.reshape(-1)
        gf = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = f(inputs)
            flat[j] = orig - eps
            fm = f(inputs)
            flat[j] = orig
            gf[j] = (fp - fm) / (2 * eps)
        grads.append(g)
    return grads


def check_numeric_gradient(fn, inputs, kwargs=None, rtol=1e-2, atol=1e-4,
                           eps=1e-3, aggregate="sum"):
    """Compare autograd gradients of ``fn`` against finite differences.

    ``fn`` maps NDArrays -> NDArray (or tuple; first output used).
    This is the TPU build's equivalent of the reference's
    check_numeric_gradient over symbols: it exercises the *tape* path.
    """
    from . import autograd
    from .ndarray import array
    kwargs = kwargs or {}
    np_inputs = [np.asarray(i, dtype=np.float64) for i in inputs]

    def scalar_f(nps):
        outs = fn(*[array(x.astype(np.float32)) for x in nps], **kwargs)
        if isinstance(outs, (list, tuple)):
            outs = outs[0]
        return float(outs.sum().asscalar())

    expected = numeric_grad(scalar_f, [x.copy() for x in np_inputs], eps=eps)

    nd_inputs = [array(x.astype(np.float32)) for x in np_inputs]
    for x in nd_inputs:
        x.attach_grad()
    with autograd.record():
        outs = fn(*nd_inputs, **kwargs)
        if isinstance(outs, (list, tuple)):
            outs = outs[0]
        loss = outs.sum()
    loss.backward()
    for i, (x, exp) in enumerate(zip(nd_inputs, expected)):
        assert_almost_equal(x.grad.asnumpy(), exp.astype(np.float32),
                            rtol=rtol, atol=atol,
                            names=(f"autograd_grad[{i}]", f"numeric_grad[{i}]"))


def check_consistency(fn, inputs, ctx_list=None, rtol=1e-4, atol=1e-5,
                      kwargs=None):
    """Run the same computation on several contexts/dtypes and compare —
    the reference's cpu-vs-gpu consistency pattern, reused as
    tpu-vs-cpu-oracle (SURVEY.md §4)."""
    from .ndarray import array
    kwargs = kwargs or {}
    if ctx_list is None:
        ctx_list = [cpu(0)]
    results = []
    for ctx in ctx_list:
        outs = fn(*[array(i, ctx=ctx) for i in inputs], **kwargs)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        results.append([o.asnumpy() for o in outs])
    base = results[0]
    for r in results[1:]:
        for b, o in zip(base, r):
            assert_almost_equal(b, o, rtol=rtol, atol=atol)
    return base


def check_symbolic_forward(sym, inputs, expected, rtol=1e-5, atol=1e-6,
                           ctx=None):
    """Evaluate a Symbol graph and compare to numpy expectation
    (reference: test_utils.check_symbolic_forward)."""
    from .ndarray import array
    args = {name: array(val) for name, val in
            zip(sym.list_arguments(), inputs)}
    outs = sym.eval(**args)
    for o, e in zip(outs, expected):
        assert_almost_equal(o.asnumpy(), e, rtol=rtol, atol=atol)


def check_symbolic_backward(sym, inputs, out_grads, expected, rtol=1e-5,
                            atol=1e-6, ctx=None):
    from .executor import Executor
    from .ndarray import array
    arg_names = sym.list_arguments()
    args = {n: array(v) for n, v in zip(arg_names, inputs)}
    grads = {n: array(np.zeros_like(v)) for n, v in zip(arg_names, inputs)}
    exe = Executor(sym, ctx, args, grads, "write", {})
    exe.forward(is_train=True)
    exe.backward([array(g) for g in out_grads])
    for n, e in zip(arg_names, expected):
        if e is None:
            continue
        assert_almost_equal(exe.grad_dict[n].asnumpy(), e, rtol=rtol,
                            atol=atol, names=(f"grad[{n}]", "expected"))
