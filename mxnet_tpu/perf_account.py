"""Per-step training performance accounting (docs/perf_playbook.md
"Reading a step breakdown"; docs/observability.md training taxonomy).

The serving plane debugs its tail span-by-span (``mxnet_tpu.tracing``);
the training plane had only aggregates — a slow ``trainer.step.seconds``
p99 was compatible with a starved input pipeline, a slow host→device
stage, or a congested gradient collective, and the MFU math lived in
``bench.py`` where no running job could read it.  This module is the
training half of that observability contract:

- **Step attribution** (:class:`StepAttribution`): each attributed
  trainer step roots a ``train.step`` trace decomposed into
  ``train.data.wait`` (iterator next + host staging — noted by the io
  layer via :func:`note_data_wait` and back-dated into the step that
  consumes the batch), ``train.h2d`` (``global_device_put`` staging),
  ``train.compute`` (dispatch → device completion of the compiled
  fwd+bwd program), and zero-length ``train.collective`` /
  ``train.optimizer`` markers (both run fused *inside* the one
  compiled program; the collective marker carries the wire-vs-logical
  byte accounting).  Same head sampling, ring, and chrome-trace export
  as serving — a training timeline opens in Perfetto next to a
  serving one.
- **Runtime MFU** (:func:`step_flops` / :func:`mfu`, promoted from
  ``bench.py``): exact per-step FLOPs from XLA's ``cost_analysis`` of
  the compiled step, divided by measured step time and the per-chip
  peak (``MXNET_PEAK_TFLOPS`` or the device-kind default), published
  as the ``train.mfu`` gauge.  Backends without cost analysis degrade
  to a NaN-safe 0 with one warning.
- **Bottleneck verdict**: over a rolling window of steps, the largest
  non-compute phase names the bottleneck — ``input_bound``
  (data wait + h2d), ``comm_bound`` (collective), else
  ``compute_bound`` — published as the ``train.bottleneck`` gauge,
  tagged on incident dumps, printed by ``tools/diagnose.py`` and the
  ``Speedometer`` log line.

Overhead contract (mirrors ``tracing``/``runtime_metrics``): with both
``MXNET_TRACE`` and ``MXNET_RUNTIME_METRICS`` off, :meth:`step_start`
returns one shared inert handle — an attribute load + branch per step —
and no XLA program is ever added in either switch position (FLOPs
accounting is metrics-gated and AOT, outside the jit cache).
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque

from . import runtime_metrics as _rm
from . import tracing as _tr
from .base import get_env

__all__ = [
    "PHASES", "VERDICTS", "StepAttribution",
    "mfu", "step_flops", "detect_peak_tflops",
    "note_data_wait", "take_data_wait",
    "current_verdict", "current_mfu", "reset",
]

_LOG = logging.getLogger("mxnet_tpu")

# breakdown phases (the `phase` label of train.step.breakdown.seconds);
# every attributed step observes all five so the per-phase histograms
# stay directly comparable and the phases tile the train.step interval
PHASES = ("data_wait", "h2d", "compute", "collective", "optimizer")

# span leaf per phase (span name = f"train.{leaf}")
_SPAN_LEAF = {"data_wait": "data.wait"}

# verdict encoding of the train.bottleneck gauge (index = gauge value)
VERDICTS = ("compute_bound", "input_bound", "comm_bound")
_VERDICT_CODE = {v: i for i, v in enumerate(VERDICTS)}

# which verdict a non-compute phase votes for; compute + the fused
# optimizer marker count as compute time
_PHASE_VERDICT = {"data_wait": "input_bound", "h2d": "input_bound",
                  "collective": "comm_bound"}


# ---------------------------------------------------------------------------
# FLOPs / MFU accounting (promoted from bench.py — one source of truth)
# ---------------------------------------------------------------------------

def mfu(n_params, B, L, dt, peak_tflops):
    """The 6NBL transformer rule: 6 * params * tokens FLOPs per step,
    over measured step seconds and the per-chip peak."""
    return 6.0 * n_params * B * L / dt / (peak_tflops * 1e12)


def step_flops(trainer, batch):
    """Exact per-step model FLOPs from XLA's cost analysis of the
    compiled train step (fwd+bwd+optimizer as one program).  The 6NBL
    transformer rule undercounts conv nets badly, so conv workloads
    need the compiler's own count.  Returns None when the backend's
    PJRT executable doesn't expose cost analysis (callers fall back to
    an analytic estimate, or report MFU 0)."""
    import jax
    try:
        shardb = trainer.shard_batch(
            *[getattr(b, "_data", b) for b in batch])
        args = (trainer.params, trainer.opt_state)
        if getattr(trainer, "compression", None) is not None:
            args = args + (trainer.residuals, jax.random.PRNGKey(0))
        compiled = trainer._step.lower(*args, *shardb).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        f = float(ca.get("flops", 0.0))
        return f if f > 0 else None
    except Exception:                            # noqa: BLE001
        return None


def detect_peak_tflops(devices=None):
    """Per-chip bf16 peak TFLOP/s for MFU: ``MXNET_PEAK_TFLOPS`` when
    set (> 0), else the device-kind default (v5p 459, v5e/"lite" 197,
    CPU 0.15 — the same table ``BENCH_PEAK_TFLOPS`` defaults from)."""
    override = float(get_env("MXNET_PEAK_TFLOPS", typ=float) or 0.0)
    if override > 0:
        return override
    if devices is None:
        try:
            import jax
            devices = jax.devices()
        except Exception:                        # noqa: BLE001
            return 0.15
    on_tpu = any(d.platform != "cpu" for d in devices)
    if not on_tpu:
        return 0.15
    kind = devices[0].device_kind.lower()
    return 197.0 if ("lite" in kind or "v5e" in kind) else 459.0


# ---------------------------------------------------------------------------
# Data-wait handoff (io layer -> the step that consumes the batch)
# ---------------------------------------------------------------------------

# thread-local: the iterator runs on the train-loop thread right before
# step(); a PrefetchingIter's producer thread notes into its own slot,
# which is never consumed — only the consumer-visible wait counts
_TLS = threading.local()


def note_data_wait(t0, t1):
    """Record the host interval one ``DataIter.next()`` took (iterator
    wait + host staging); the next :meth:`StepAttribution.step_start`
    on this thread consumes it as the step's ``train.data.wait``."""
    _TLS.data_wait = (t0, t1)


def take_data_wait():
    """Pop the pending data-wait interval, or None."""
    iv = getattr(_TLS, "data_wait", None)
    if iv is not None:
        _TLS.data_wait = None
    return iv


# ---------------------------------------------------------------------------
# Last-published snapshot (Speedometer / diagnose read these without a
# trainer handle; single-writer per publish, torn reads are benign)
# ---------------------------------------------------------------------------

_LAST = {"verdict": None, "mfu": 0.0}


def current_verdict():
    """The verdict of the most recent attributed step in this process
    (any trainer), or None before the first one."""
    return _LAST["verdict"]


def current_mfu():
    """MFU over the attribution window of the most recent attributed
    step (0.0 when FLOPs are unknown)."""
    return _LAST["mfu"]


def reset():
    """Clear process-level attribution state (tests)."""
    _LAST["verdict"] = None
    _LAST["mfu"] = 0.0
    _TLS.data_wait = None


# ---------------------------------------------------------------------------
# Step handles
# ---------------------------------------------------------------------------

class _InertPhase:
    """No-op phase context (the off path)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_INERT_PHASE = _InertPhase()


class _InertHandle:
    """Shared do-nothing step handle: what :meth:`step_start` returns
    when both tracing and metrics are off.  One global instance; every
    method is a constant-time no-op."""

    __slots__ = ()
    active = False
    root = None

    def phase(self, name, **tags):
        return _INERT_PHASE

    def record(self, name, t0, t1, **tags):
        return None

    def mark(self, name, **tags):
        return None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_INERT = _InertHandle()


class _PhaseTimer:
    """``with h.phase("h2d"):`` — times the block and records it."""

    __slots__ = ("_h", "_name", "_tags", "_t0")

    def __init__(self, h, name, tags):
        self._h = h
        self._name = name
        self._tags = tags
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        if exc_type is not None:
            self._tags["error"] = exc_type.__name__
        self._h.record(self._name, self._t0, t1, **self._tags)
        return False


class _StepHandle:
    """One attributed step: phase accumulator + the ``train.step`` root
    span.  Enter it (``with h:``) around the step body so thread-local
    ``tracing.tag()`` calls (watchdog straggler/timeout events) land on
    the root; exiting ends the root and publishes the breakdown."""

    __slots__ = ("att", "root", "seconds", "t_begin", "t_end")

    def __init__(self, att, root, t_begin):
        self.att = att
        self.root = root
        self.seconds = {}
        self.t_begin = t_begin
        self.t_end = None

    active = True

    def phase(self, name, **tags):
        """Context manager timing one phase of this step."""
        return _PhaseTimer(self, name, tags)

    def record(self, name, t0, t1, **tags):
        """Add an already-timed interval to phase ``name`` and record
        the matching ``train.*`` span (no-op span when unsampled)."""
        self.seconds[name] = self.seconds.get(name, 0.0) + (t1 - t0)
        leaf = _SPAN_LEAF.get(name, name)
        _tr.record_span(f"train.{leaf}", self.root, t0, t1,
                        tags or None)

    def mark(self, name, **tags):
        """Zero-length phase marker: the phase runs fused inside
        another interval (the one-program step executes collective +
        optimizer inside ``train.compute``), so it contributes 0s to
        the breakdown while its tags carry the accounting."""
        t = time.perf_counter()
        self.record(name, t, t, **tags)

    def __enter__(self):
        if self.root.sampled:
            self.root.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.t_end = time.perf_counter()
        if self.root.sampled:
            self.root.__exit__(exc_type, exc, tb)
        self.att._publish(self)
        return False


class StepAttribution:
    """Per-trainer step-time attribution, windowed MFU, and the
    bottleneck verdict.

    Owned by one train-loop thread (no internal locking), mirroring
    :class:`~.parallel.supervisor.StepWatchdog`.  ``ShardedTrainer``
    drives it from ``step()``; fake/numpy trainers (tests, the
    diagnose trace smoke) drive the same handle API directly::

        att = StepAttribution()
        h = att.step_start()
        with h:                      # roots the train.step span
            with h.phase("data_wait"):
                batch = it.next()
            with h.phase("h2d"):
                dev_batch = stage(batch)
            with h.phase("compute"):
                loss = run(dev_batch)
            h.mark("collective", fused=True)
            h.mark("optimizer", fused=True)
        # exit published breakdown histograms, MFU, and the verdict

    ``threshold`` is the window fraction the largest non-compute phase
    must reach before the verdict leaves ``compute_bound``.
    """

    def __init__(self, window=32, threshold=0.25, peak_tflops=None):
        self._window = deque(maxlen=int(window))
        self.threshold = float(threshold)
        self.peak_tflops = (detect_peak_tflops()
                            if peak_tflops is None else
                            float(peak_tflops))
        self.flops_per_step = None      # unknown until note_flops
        self._flops_warned = False
        self._verdict = None
        self._mfu = 0.0
        self._steps = 0

    @property
    def active(self):
        """True when either observability switch is on — the gate the
        instrumented trainer checks before paying any per-step cost."""
        return _rm._ENABLED or _tr._ENABLED

    # ------------------------------------------------------------ flops
    def note_flops(self, flops):
        """Install the per-step FLOP count (from :func:`step_flops` or
        an analytic estimate).  None/0 — no cost analysis on this
        backend — degrades to MFU 0 with one warning, never NaN."""
        if flops:
            self.flops_per_step = float(flops)
        else:
            self.flops_per_step = 0.0
            if not self._flops_warned:
                self._flops_warned = True
                _LOG.warning(
                    "perf_account: step FLOPs unavailable (backend "
                    "exposes no cost_analysis) — train.mfu reports 0")

    # ------------------------------------------------------------- steps
    def step_start(self, **tags):
        """Begin one attributed step.  Returns the step handle — the
        shared inert one when tracing and metrics are both off.  A
        pending data-wait interval (:func:`note_data_wait`) is consumed
        here: the root span is back-dated to its start so the phase
        spans tile the ``train.step`` interval."""
        if not (_rm._ENABLED or _tr._ENABLED):
            return _INERT
        pending = take_data_wait()
        root = _tr.trace("train.step", **tags)
        h = _StepHandle(self, root, time.perf_counter())
        if pending is not None:
            t0, t1 = pending
            if root.sampled:
                root.t0 = min(root.t0, t0)
            h.t_begin = min(h.t_begin, t0)
            h.record("data_wait", t0, t1)
        return h

    # ----------------------------------------------------------- publish
    def _publish(self, h):
        dt = max(h.t_end - h.t_begin, 0.0)
        self._window.append((dt, h.seconds))
        self._steps += 1
        self._verdict = self._compute_verdict()
        self._mfu = self._compute_mfu()
        _LAST["verdict"] = self._verdict
        _LAST["mfu"] = self._mfu
        if _rm._ENABLED:
            for p in PHASES:
                _rm.TRAIN_STEP_BREAKDOWN_SECONDS.observe(
                    h.seconds.get(p, 0.0), phase=p)
            tid = h.root.trace_id if h.root.sampled else None
            _rm.TRAINER_STEP_SECONDS.observe(dt, exemplar=tid)
            _rm.TRAIN_MFU.set(self._mfu)
            _rm.TRAIN_BOTTLENECK.set(_VERDICT_CODE[self._verdict])

    def _compute_verdict(self):
        wall = sum(dt for dt, _ in self._window)
        if wall <= 0:
            return "compute_bound"
        votes = {"input_bound": 0.0, "comm_bound": 0.0}
        for _, secs in self._window:
            for p, v in _PHASE_VERDICT.items():
                votes[v] += secs.get(p, 0.0)
        top = max(votes, key=votes.get)
        if votes[top] / wall >= self.threshold:
            return top
        return "compute_bound"

    def _compute_mfu(self):
        if not self.flops_per_step or self.peak_tflops <= 0:
            return 0.0
        wall = sum(dt for dt, _ in self._window)
        if wall <= 0:
            return 0.0
        return (self.flops_per_step * len(self._window)
                / wall / (self.peak_tflops * 1e12))

    # ------------------------------------------------------------ readers
    def verdict(self):
        """Current windowed verdict, or None before the first step."""
        return self._verdict

    def mfu_value(self):
        """MFU over the current window (0.0 while FLOPs unknown)."""
        return self._mfu

    def phase_means(self):
        """Mean seconds per phase over the window."""
        n = len(self._window)
        if not n:
            return {p: 0.0 for p in PHASES}
        return {p: sum(secs.get(p, 0.0)
                       for _, secs in self._window) / n
                for p in PHASES}

    def summary(self):
        """One JSON-ready block: window means, fractions of step time,
        verdict, MFU (the BENCH ``attribution`` payload)."""
        means = self.phase_means()
        wall = sum(dt for dt, _ in self._window)
        n = len(self._window)
        step_mean = wall / n if n else 0.0
        frac = {p: (means[p] / step_mean if step_mean > 0 else 0.0)
                for p in PHASES}
        return {"steps": self._steps,
                "step_seconds_mean": round(step_mean, 6),
                "phase_seconds_mean":
                    {p: round(means[p], 6) for p in PHASES},
                "phase_fraction":
                    {p: round(frac[p], 4) for p in PHASES},
                "verdict": self._verdict,
                "mfu": round(self._mfu, 4)}

    def debug_state(self):
        """Incident-dump payload (rides supervisor/flight dumps)."""
        out = self.summary()
        out["flops_per_step"] = self.flops_per_step
        out["peak_tflops"] = self.peak_tflops
        return out
