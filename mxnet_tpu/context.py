"""Device context model mapped onto JAX/PJRT devices.

Reference surface: ``python/mxnet/context.py`` (``Context``, ``cpu()``,
``gpu()``, ``current_context``).  TPU-native redesign:

- ``mx.tpu(i)`` is first-class; ``mx.gpu(i)`` is an *alias* for the i-th
  accelerator so reference-era scripts written against ``mx.gpu`` run
  unchanged on TPU.
- A ``Context`` resolves to a concrete ``jax.Device``; array placement uses
  ``jax.device_put`` and sharding machinery rather than the reference's
  per-device CUDA streams.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context",
           "num_gpus", "num_tpus", "gpu_memory_info"]


class Context:
    """Device context (reference: python/mxnet/context.py -> class Context)."""

    # devtype ids kept compatible with the reference enum where it exists
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}
    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise MXNetError(f"unknown device type {device_type!r}")
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx: Optional[Context] = None

    # -- identity ----------------------------------------------------------
    @property
    def device_type(self) -> str:
        return self.devtype2str[self.device_typeid]

    @property
    def _canonical_typeid(self):
        # gpu is an alias for the i-th accelerator == tpu (module docstring)
        return 6 if self.device_typeid == 2 else self.device_typeid

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self._canonical_typeid == other._canonical_typeid
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self._canonical_typeid, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- JAX resolution ----------------------------------------------------
    def jax_device(self) -> "jax.Device":
        """Resolve to a concrete jax.Device.

        cpu -> a host-platform device; tpu/gpu -> the i-th accelerator
        (any non-cpu platform: tpu, axon tunnel, gpu).
        """
        devs = _devices_for(self.device_type)
        if self.device_id >= len(devs):
            raise MXNetError(
                f"context {self} out of range: only {len(devs)} "
                f"{self.device_type} device(s) visible to JAX")
        return devs[self.device_id]

    def empty_cache(self):
        """Release cached device memory (reference: Context.empty_cache).

        PJRT owns pooling; this is a best-effort hint."""
        try:
            self.jax_device().memory_stats()
        except Exception:
            pass

    # -- scoping -----------------------------------------------------------
    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = self._old_ctx
        return False


def _accel_devices():
    # local (addressable) accelerators only: device counts must agree
    # with what Context can actually address in a multi-process job
    return [d for d in jax.local_devices() if d.platform != "cpu"]


def _devices_for(device_type: str):
    # Contexts address THIS process's devices: under jax.distributed each
    # process may only touch its local (addressable) devices — global
    # jax.devices() entries from other hosts cannot back an NDArray.
    if device_type in ("cpu", "cpu_pinned", "cpu_shared"):
        try:
            return jax.local_devices(backend="cpu")
        except RuntimeError:
            # cpu platform not initialised alongside an accelerator; fall
            # back to whatever the default platform is.
            return jax.local_devices()
    accel = _accel_devices()
    if accel:
        return accel
    # No accelerator present: cpu devices stand in (e.g. the 8-device
    # virtual CPU mesh used by the test suite).
    return jax.local_devices()


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias for the i-th accelerator; on TPU machines this IS a TPU chip."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_gpus() -> int:
    return len(_accel_devices())


def num_tpus() -> int:
    return len(_accel_devices())


def gpu_memory_info(device_id: int = 0):
    """(free, total) bytes for the i-th accelerator, when the platform
    reports it (reference: mx.context.gpu_memory_info)."""
    dev = Context("gpu", device_id).jax_device()
    stats = dev.memory_stats() or {}
    total = stats.get("bytes_limit", 0)
    used = stats.get("bytes_in_use", 0)
    return (total - used, total)


def current_context() -> Context:
    ctx = getattr(Context._default_ctx, "value", None)
    if ctx is None:
        ctx = default_context()
    return ctx


def default_context() -> Context:
    """Accelerator if present else cpu (the bench path wants the chip)."""
    if _accel_devices():
        return Context("tpu", 0)
    return Context("cpu", 0)
