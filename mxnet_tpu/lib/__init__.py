"""Native (C++) runtime components, bound via ctypes (see nativelib.py)."""
from . import nativelib

__all__ = ["nativelib"]
