// Native IO runtime for mxnet_tpu.
//
// Reference: dmlc-core's C++ RecordIO (include/dmlc/recordio.h,
// src/recordio.cc) and the C++ iterator tier (src/io/iter_csv.cc) —
// SURVEY.md §2.1 dmlc-core + Data iterators rows.  The TPU build keeps
// compute on XLA, but the host-side input path (record scanning, framed
// reads, CSV tokenizing) is byte-churning work Python does slowly; this
// library is that tier, exposed over a plain C ABI consumed via ctypes
// (mxnet_tpu/lib/nativelib.py), with the pure-Python implementation as
// the always-available fallback.
//
// Format (byte-compatible with mxnet_tpu/recordio.py and dmlc):
//   [magic:u32 LE][lrec:u32 LE][payload][pad to 4B]
//   lrec = cflag<<29 | len ; multipart cflags 1/2/3 re-join with the
//   magic word (payloads containing the magic are split on write).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Reader {
  FILE* f = nullptr;
  int64_t size = 0;
};

inline int64_t pad4(int64_t n) { return (4 - n % 4) % 4; }

}  // namespace

extern "C" {

// ---------------------------------------------------------------- reader
void* mxrec_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new Reader();
  r->f = f;
  std::fseek(f, 0, SEEK_END);
  r->size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  return r;
}

void mxrec_close(void* h) {
  if (!h) return;
  auto* r = static_cast<Reader*>(h);
  if (r->f) std::fclose(r->f);
  delete r;
}

// Scan the file, writing the byte offset of each *logical* record
// (multipart = one record) into `offsets` (capacity `cap`; pass cap=0 to
// count only).  Returns the record count, or -1 on a framing error.
int64_t mxrec_index(void* h, int64_t* offsets, int64_t cap) {
  auto* r = static_cast<Reader*>(h);
  std::fseek(r->f, 0, SEEK_SET);
  int64_t pos = 0, count = 0;
  while (pos + 8 <= r->size) {
    int64_t record_start = pos;
    bool logical_start = true;
    // walk the (possibly multipart) frame chain
    while (true) {
      uint32_t head[2];
      if (std::fseek(r->f, pos, SEEK_SET) != 0) return -1;
      if (std::fread(head, 4, 2, r->f) != 2) return count;  // EOF
      if (head[0] != kMagic) return -1;
      uint32_t cflag = head[1] >> 29;
      int64_t len = head[1] & kLenMask;
      pos += 8 + len + pad4(len);
      if (logical_start && cflag != 0 && cflag != 1) return -1;
      logical_start = false;
      if (cflag == 0 || cflag == 3) break;
    }
    if (offsets && count < cap) offsets[count] = record_start;
    ++count;
  }
  return count;
}

// Read the logical record at `offset`, re-joining multipart frames with
// the magic word.  Returns payload length; if it exceeds `cap` nothing is
// written and the required size is returned (call again with a bigger
// buffer).  Returns -1 on framing errors.
int64_t mxrec_read_at(void* h, int64_t offset, char* buf, int64_t cap) {
  auto* r = static_cast<Reader*>(h);
  int64_t pos = offset, total = 0;
  bool measuring_done = false;
  // first pass: measure; second: copy (single pass when it fits)
  std::vector<std::pair<int64_t, int64_t>> spans;  // (file_pos, len)
  while (true) {
    uint32_t head[2];
    if (std::fseek(r->f, pos, SEEK_SET) != 0) return -1;
    if (std::fread(head, 4, 2, r->f) != 2) return -1;
    if (head[0] != kMagic) return -1;
    uint32_t cflag = head[1] >> 29;
    int64_t len = head[1] & kLenMask;
    if (!spans.empty()) total += 4;  // joining magic
    spans.emplace_back(pos + 8, len);
    total += len;
    pos += 8 + len + pad4(len);
    if (cflag == 0 || cflag == 3) break;
  }
  if (total > cap || !buf) return total;
  char* out = buf;
  for (size_t i = 0; i < spans.size(); ++i) {
    if (i > 0) {
      std::memcpy(out, &kMagic, 4);
      out += 4;
    }
    std::fseek(r->f, spans[i].first, SEEK_SET);
    if (std::fread(out, 1, spans[i].second, r->f) !=
        static_cast<size_t>(spans[i].second))
      return -1;
    out += spans[i].second;
  }
  (void)measuring_done;
  return total;
}

// ---------------------------------------------------------------- writer
void* mxrec_create(const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  auto* r = new Reader();
  r->f = f;
  return r;
}

// Write one logical record, splitting embedded magic words into multipart
// frames exactly like dmlc::RecordIOWriter.  Returns bytes written, -1 on
// IO error.
int64_t mxrec_write(void* h, const char* data, int64_t len) {
  auto* r = static_cast<Reader*>(h);
  // find split points at embedded magics
  std::vector<std::pair<const char*, int64_t>> parts;
  const char* p = data;
  const char* end = data + len;
  const char* part_start = p;
  while (p + 4 <= end) {
    uint32_t w;
    std::memcpy(&w, p, 4);
    if (w == kMagic) {
      parts.emplace_back(part_start, p - part_start);
      p += 4;
      part_start = p;
    } else {
      ++p;
    }
  }
  parts.emplace_back(part_start, end - part_start);
  int64_t written = 0;
  const size_t n = parts.size();
  for (size_t i = 0; i < n; ++i) {
    uint32_t cflag = 0;
    if (n > 1) cflag = (i == 0) ? 1 : (i == n - 1 ? 3 : 2);
    int64_t plen = parts[i].second;
    uint32_t lrec = (cflag << 29) | static_cast<uint32_t>(plen);
    if (std::fwrite(&kMagic, 4, 1, r->f) != 1) return -1;
    if (std::fwrite(&lrec, 4, 1, r->f) != 1) return -1;
    if (plen && std::fwrite(parts[i].first, 1, plen, r->f) !=
                    static_cast<size_t>(plen))
      return -1;
    static const char zeros[4] = {0, 0, 0, 0};
    int64_t pad = pad4(plen);
    if (pad && std::fwrite(zeros, 1, pad, r->f) !=
                   static_cast<size_t>(pad))
      return -1;
    written += 8 + plen + pad;
  }
  return written;
}

// ------------------------------------------------------------------- csv
// Count values and rows of a comma/newline-separated float file.
// Returns rows; *n_vals gets the total value count; -1 on open failure.
int64_t mxcsv_shape(const char* path, int64_t* n_vals) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t rows = 0, vals = 0;
  bool in_field = false, line_had_data = false;
  int c;
  char bufc[1 << 16];
  size_t got;
  while ((got = std::fread(bufc, 1, sizeof bufc, f)) > 0) {
    for (size_t i = 0; i < got; ++i) {
      c = bufc[i];
      if (c == ',' || c == '\n') {
        if (in_field) ++vals;
        in_field = false;
        if (c == '\n') {
          if (line_had_data) ++rows;
          line_had_data = false;
        }
      } else if (c != '\r' && c != ' ' && c != '\t') {
        in_field = true;
        line_had_data = true;
      }
    }
  }
  if (in_field) ++vals;
  if (line_had_data) ++rows;
  std::fclose(f);
  *n_vals = vals;
  return rows;
}

// Parse floats into `out` (capacity cap).  Returns values parsed, -1 on
// open failure, -2 on overflow, -3 on a non-numeric field (e.g. a CSV
// header) — callers must fail loudly, matching np.loadtxt's ValueError.
int64_t mxcsv_parse(const char* path, float* out, int64_t cap) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  // stream with a field buffer: fields never exceed 64 chars for floats
  char field[64];
  int flen = 0;
  int64_t n = 0;
  char bufc[1 << 16];
  size_t got;
  int err = 0;
  auto flush = [&]() -> bool {
    if (flen == 0) return true;
    field[flen] = 0;
    if (n >= cap) { err = -2; return false; }
    char* endp = nullptr;
    float v = std::strtof(field, &endp);
    // trailing spaces are fine; any other unconsumed char is not a float
    while (endp && (*endp == ' ' || *endp == '\t')) ++endp;
    if (endp == field || (endp && *endp != 0)) { err = -3; return false; }
    out[n++] = v;
    flen = 0;
    return true;
  };
  while ((got = std::fread(bufc, 1, sizeof bufc, f)) > 0) {
    for (size_t i = 0; i < got; ++i) {
      char c = bufc[i];
      if (c == ',' || c == '\n' || c == '\r') {
        if (!flush()) { std::fclose(f); return err; }
      } else if (flen < 63) {
        field[flen++] = c;
      }
    }
  }
  bool ok = flush();
  std::fclose(f);
  return ok ? n : err;
}

int mxnative_abi_version() { return 1; }

}  // extern "C"

// --------------------------------------------------------------------------
// Threaded JPEG decode tier (reference: src/io/iter_image_recordio_2.cc —
// the reference's C++ decode/augment worker POOL; SURVEY.md §2.1 Data
// iterators, §7.3).  One C call decodes a whole batch on OS threads:
// libjpeg DCT-domain scaling (scale_denom) toward the resize target, a
// fused bilinear resize+crop gather (no intermediate full-size image),
// optional horizontal mirror, CHW uint8 output.  Crop positions come in
// as fractions so augmentation randomness stays under Python's seeded
// RNG while all byte churn happens here, GIL-free.
// --------------------------------------------------------------------------
#ifndef MXNATIVE_NO_JPEG

#include <jpeglib.h>

#include <algorithm>
#include <atomic>
#include <csetjmp>
#include <thread>

namespace {

struct JErr {
  jpeg_error_mgr mgr;
  std::jmp_buf jb;
};

void jerr_exit(j_common_ptr cinfo) {
  std::longjmp(reinterpret_cast<JErr*>(cinfo->err)->jb, 1);
}

void jerr_silent(j_common_ptr, int) {}

bool decode_one(const uint8_t* buf, int64_t len, int min_side,
                std::vector<uint8_t>* px, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jerr_exit;
  jerr.mgr.emit_message = jerr_silent;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  if (min_side > 0) {
    // largest denom in {1,2,4,8} that keeps the short side >= target:
    // 1/denom decode happens in the DCT domain — decoding a 4x-reduced
    // image costs ~1/16th the IDCT work
    unsigned denom = 1;
    unsigned short_side = std::min(cinfo.image_width, cinfo.image_height);
    while (denom < 8 && short_side / (denom * 2) >=
                            static_cast<unsigned>(min_side))
      denom *= 2;
    cinfo.scale_num = 1;
    cinfo.scale_denom = denom;
  }
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_components != 3) {  // grayscale promoted by JCS_RGB;
    jpeg_destroy_decompress(&cinfo);   // anything else is unsupported
    return false;
  }
  *w = static_cast<int>(cinfo.output_width);
  *h = static_cast<int>(cinfo.output_height);
  px->resize(static_cast<size_t>(*w) * *h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW rp = px->data() +
                  static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &rp, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Fused bilinear resize(short side -> R) + crop(out_h x out_w at
// fractional offset) + mirror, sampling straight from the decoded image
// into CHW uint8 output.
void resize_crop(const std::vector<uint8_t>& px, int w0, int h0,
                 int resize_min, int out_h, int out_w, float cy_frac,
                 float cx_frac, bool mirror, uint8_t* out) {
  float scale = 1.0f;
  if (resize_min > 0)
    scale = static_cast<float>(resize_min) / std::min(w0, h0);
  int rw = std::max(out_w, static_cast<int>(w0 * scale + 0.5f));
  int rh = std::max(out_h, static_cast<int>(h0 * scale + 0.5f));
  float sx = static_cast<float>(w0) / rw;
  float sy = static_cast<float>(h0) / rh;
  // INTEGER crop offsets, exactly like the Python/cv2 tier (randint /
  // floor-div-2 center) — a fractional offset is a half-pixel phase
  // shift versus that tier.  frac < 0 = center crop; otherwise the
  // fraction maps uniformly onto {0..range} inclusive.
  auto crop_at = [](float frac, int range) -> float {
    if (frac < 0.0f) return static_cast<float>(range / 2);
    return static_cast<float>(
        std::min(static_cast<int>(frac * (range + 1)), range));
  };
  float cy = crop_at(cy_frac, rh - out_h);
  float cx = crop_at(cx_frac, rw - out_w);
  const size_t plane = static_cast<size_t>(out_h) * out_w;
  for (int i = 0; i < out_h; ++i) {
    float fy = (cy + i + 0.5f) * sy - 0.5f;
    fy = std::min(std::max(fy, 0.0f), static_cast<float>(h0 - 1));
    int y0 = static_cast<int>(fy);
    int y1 = std::min(y0 + 1, h0 - 1);
    float wy = fy - y0;
    for (int j = 0; j < out_w; ++j) {
      float fx = (cx + j + 0.5f) * sx - 0.5f;
      fx = std::min(std::max(fx, 0.0f), static_cast<float>(w0 - 1));
      int x0 = static_cast<int>(fx);
      int x1 = std::min(x0 + 1, w0 - 1);
      float wx = fx - x0;
      const uint8_t* p00 = &px[(static_cast<size_t>(y0) * w0 + x0) * 3];
      const uint8_t* p01 = &px[(static_cast<size_t>(y0) * w0 + x1) * 3];
      const uint8_t* p10 = &px[(static_cast<size_t>(y1) * w0 + x0) * 3];
      const uint8_t* p11 = &px[(static_cast<size_t>(y1) * w0 + x1) * 3];
      int jo = mirror ? out_w - 1 - j : j;
      for (int c = 0; c < 3; ++c) {
        float v = (1 - wy) * ((1 - wx) * p00[c] + wx * p01[c]) +
                  wy * ((1 - wx) * p10[c] + wx * p11[c]);
        out[c * plane + static_cast<size_t>(i) * out_w + jo] =
            static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

int mxnative_has_jpeg() { return 1; }

// Decode n JPEGs into out (n, 3, out_h, out_w) uint8 on n_threads OS
// threads.  status[i]: 0 = ok, 1 = decode failed (caller re-tries that
// image on its fallback path).  Returns the success count.
int64_t mxjpeg_decode_batch(const uint8_t* const* bufs,
                            const int64_t* lens, int64_t n,
                            int resize_min, int out_h, int out_w,
                            const float* cy_frac, const float* cx_frac,
                            const uint8_t* mirror, uint8_t* out,
                            uint8_t* status, int64_t n_threads) {
  const size_t stride = static_cast<size_t>(3) * out_h * out_w;
  std::atomic<int64_t> next(0), ok_count(0);
  auto worker = [&]() {
    std::vector<uint8_t> px;
    int64_t i;
    while ((i = next.fetch_add(1)) < n) {
      int w0 = 0, h0 = 0;
      if (!decode_one(bufs[i], lens[i], resize_min, &px, &w0, &h0) ||
          w0 < 1 || h0 < 1) {
        status[i] = 1;
        continue;
      }
      resize_crop(px, w0, h0, resize_min, out_h, out_w, cy_frac[i],
                  cx_frac[i], mirror[i] != 0, out + i * stride);
      status[i] = 0;
      ok_count.fetch_add(1);
    }
  };
  int64_t nt = std::min<int64_t>(std::max<int64_t>(n_threads, 1), n);
  std::vector<std::thread> pool;
  for (int64_t t = 1; t < nt; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  return ok_count.load();
}

}  // extern "C"

#else  // MXNATIVE_NO_JPEG

extern "C" {
int mxnative_has_jpeg() { return 0; }
}

#endif  // MXNATIVE_NO_JPEG
