// Native IO runtime for mxnet_tpu.
//
// Reference: dmlc-core's C++ RecordIO (include/dmlc/recordio.h,
// src/recordio.cc) and the C++ iterator tier (src/io/iter_csv.cc) —
// SURVEY.md §2.1 dmlc-core + Data iterators rows.  The TPU build keeps
// compute on XLA, but the host-side input path (record scanning, framed
// reads, CSV tokenizing) is byte-churning work Python does slowly; this
// library is that tier, exposed over a plain C ABI consumed via ctypes
// (mxnet_tpu/lib/nativelib.py), with the pure-Python implementation as
// the always-available fallback.
//
// Format (byte-compatible with mxnet_tpu/recordio.py and dmlc):
//   [magic:u32 LE][lrec:u32 LE][payload][pad to 4B]
//   lrec = cflag<<29 | len ; multipart cflags 1/2/3 re-join with the
//   magic word (payloads containing the magic are split on write).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct Reader {
  FILE* f = nullptr;
  int64_t size = 0;
};

inline int64_t pad4(int64_t n) { return (4 - n % 4) % 4; }

}  // namespace

extern "C" {

// ---------------------------------------------------------------- reader
void* mxrec_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new Reader();
  r->f = f;
  std::fseek(f, 0, SEEK_END);
  r->size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  return r;
}

void mxrec_close(void* h) {
  if (!h) return;
  auto* r = static_cast<Reader*>(h);
  if (r->f) std::fclose(r->f);
  delete r;
}

// Scan the file, writing the byte offset of each *logical* record
// (multipart = one record) into `offsets` (capacity `cap`; pass cap=0 to
// count only).  Returns the record count, or -1 on a framing error.
int64_t mxrec_index(void* h, int64_t* offsets, int64_t cap) {
  auto* r = static_cast<Reader*>(h);
  std::fseek(r->f, 0, SEEK_SET);
  int64_t pos = 0, count = 0;
  while (pos + 8 <= r->size) {
    int64_t record_start = pos;
    bool logical_start = true;
    // walk the (possibly multipart) frame chain
    while (true) {
      uint32_t head[2];
      if (std::fseek(r->f, pos, SEEK_SET) != 0) return -1;
      if (std::fread(head, 4, 2, r->f) != 2) return count;  // EOF
      if (head[0] != kMagic) return -1;
      uint32_t cflag = head[1] >> 29;
      int64_t len = head[1] & kLenMask;
      pos += 8 + len + pad4(len);
      if (logical_start && cflag != 0 && cflag != 1) return -1;
      logical_start = false;
      if (cflag == 0 || cflag == 3) break;
    }
    if (offsets && count < cap) offsets[count] = record_start;
    ++count;
  }
  return count;
}

// Read the logical record at `offset`, re-joining multipart frames with
// the magic word.  Returns payload length; if it exceeds `cap` nothing is
// written and the required size is returned (call again with a bigger
// buffer).  Returns -1 on framing errors.
int64_t mxrec_read_at(void* h, int64_t offset, char* buf, int64_t cap) {
  auto* r = static_cast<Reader*>(h);
  int64_t pos = offset, total = 0;
  bool measuring_done = false;
  // first pass: measure; second: copy (single pass when it fits)
  std::vector<std::pair<int64_t, int64_t>> spans;  // (file_pos, len)
  while (true) {
    uint32_t head[2];
    if (std::fseek(r->f, pos, SEEK_SET) != 0) return -1;
    if (std::fread(head, 4, 2, r->f) != 2) return -1;
    if (head[0] != kMagic) return -1;
    uint32_t cflag = head[1] >> 29;
    int64_t len = head[1] & kLenMask;
    if (!spans.empty()) total += 4;  // joining magic
    spans.emplace_back(pos + 8, len);
    total += len;
    pos += 8 + len + pad4(len);
    if (cflag == 0 || cflag == 3) break;
  }
  if (total > cap || !buf) return total;
  char* out = buf;
  for (size_t i = 0; i < spans.size(); ++i) {
    if (i > 0) {
      std::memcpy(out, &kMagic, 4);
      out += 4;
    }
    std::fseek(r->f, spans[i].first, SEEK_SET);
    if (std::fread(out, 1, spans[i].second, r->f) !=
        static_cast<size_t>(spans[i].second))
      return -1;
    out += spans[i].second;
  }
  (void)measuring_done;
  return total;
}

// ---------------------------------------------------------------- writer
void* mxrec_create(const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  auto* r = new Reader();
  r->f = f;
  return r;
}

// Write one logical record, splitting embedded magic words into multipart
// frames exactly like dmlc::RecordIOWriter.  Returns bytes written, -1 on
// IO error.
int64_t mxrec_write(void* h, const char* data, int64_t len) {
  auto* r = static_cast<Reader*>(h);
  // find split points at embedded magics
  std::vector<std::pair<const char*, int64_t>> parts;
  const char* p = data;
  const char* end = data + len;
  const char* part_start = p;
  while (p + 4 <= end) {
    uint32_t w;
    std::memcpy(&w, p, 4);
    if (w == kMagic) {
      parts.emplace_back(part_start, p - part_start);
      p += 4;
      part_start = p;
    } else {
      ++p;
    }
  }
  parts.emplace_back(part_start, end - part_start);
  int64_t written = 0;
  const size_t n = parts.size();
  for (size_t i = 0; i < n; ++i) {
    uint32_t cflag = 0;
    if (n > 1) cflag = (i == 0) ? 1 : (i == n - 1 ? 3 : 2);
    int64_t plen = parts[i].second;
    uint32_t lrec = (cflag << 29) | static_cast<uint32_t>(plen);
    if (std::fwrite(&kMagic, 4, 1, r->f) != 1) return -1;
    if (std::fwrite(&lrec, 4, 1, r->f) != 1) return -1;
    if (plen && std::fwrite(parts[i].first, 1, plen, r->f) !=
                    static_cast<size_t>(plen))
      return -1;
    static const char zeros[4] = {0, 0, 0, 0};
    int64_t pad = pad4(plen);
    if (pad && std::fwrite(zeros, 1, pad, r->f) !=
                   static_cast<size_t>(pad))
      return -1;
    written += 8 + plen + pad;
  }
  return written;
}

// ------------------------------------------------------------------- csv
// Count values and rows of a comma/newline-separated float file.
// Returns rows; *n_vals gets the total value count; -1 on open failure.
int64_t mxcsv_shape(const char* path, int64_t* n_vals) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  int64_t rows = 0, vals = 0;
  bool in_field = false, line_had_data = false;
  int c;
  char bufc[1 << 16];
  size_t got;
  while ((got = std::fread(bufc, 1, sizeof bufc, f)) > 0) {
    for (size_t i = 0; i < got; ++i) {
      c = bufc[i];
      if (c == ',' || c == '\n') {
        if (in_field) ++vals;
        in_field = false;
        if (c == '\n') {
          if (line_had_data) ++rows;
          line_had_data = false;
        }
      } else if (c != '\r' && c != ' ' && c != '\t') {
        in_field = true;
        line_had_data = true;
      }
    }
  }
  if (in_field) ++vals;
  if (line_had_data) ++rows;
  std::fclose(f);
  *n_vals = vals;
  return rows;
}

// Parse floats into `out` (capacity cap).  Returns values parsed, -1 on
// open failure, -2 on overflow, -3 on a non-numeric field (e.g. a CSV
// header) — callers must fail loudly, matching np.loadtxt's ValueError.
int64_t mxcsv_parse(const char* path, float* out, int64_t cap) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  // stream with a field buffer: fields never exceed 64 chars for floats
  char field[64];
  int flen = 0;
  int64_t n = 0;
  char bufc[1 << 16];
  size_t got;
  int err = 0;
  auto flush = [&]() -> bool {
    if (flen == 0) return true;
    field[flen] = 0;
    if (n >= cap) { err = -2; return false; }
    char* endp = nullptr;
    float v = std::strtof(field, &endp);
    // trailing spaces are fine; any other unconsumed char is not a float
    while (endp && (*endp == ' ' || *endp == '\t')) ++endp;
    if (endp == field || (endp && *endp != 0)) { err = -3; return false; }
    out[n++] = v;
    flen = 0;
    return true;
  };
  while ((got = std::fread(bufc, 1, sizeof bufc, f)) > 0) {
    for (size_t i = 0; i < got; ++i) {
      char c = bufc[i];
      if (c == ',' || c == '\n' || c == '\r') {
        if (!flush()) { std::fclose(f); return err; }
      } else if (flen < 63) {
        field[flen++] = c;
      }
    }
  }
  bool ok = flush();
  std::fclose(f);
  return ok ? n : err;
}

int mxnative_abi_version() { return 1; }

}  // extern "C"
