// shlo_runner: framework-free PJRT consumer of exported StableHLO
// artifacts (docs/frontends.md §2; reference: cpp-package consumes the
// C ABI directly, SURVEY.md §2.3).
//
// Loads a PJRT C-API plugin (.so exporting GetPjrtApi), compiles the
// MLIR module emitted by mxnet_tpu.deploy.export_stablehlo(...,
// emit_text=True), feeds raw binary input files, runs one execution on
// the first addressable device, and writes each output as raw bytes to
// <out_prefix>.<i>.bin plus a one-line "<dtype> <dims...>" header to
// <out_prefix>.<i>.meta.  No Python, no framework — the deployment
// boundary is the compiled program.
//
//   shlo_runner <plugin.so> <module.mlir> <compile_options.pb|-> \
//               <out_prefix> [--opt name=i:42 | --opt name=s:text ...] \
//               [dtype@d0xd1x...@file.bin ...]
//
// --opt passes PJRT_NamedValue client-create options (some plugins,
// e.g. the axon TPU tunnel, require platform-specific ones).
//
// Build: ci/runtime_functions.sh native_build (g++ -ldl; the PJRT C API
// header comes from the bundled XLA headers).
#include <dlfcn.h>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

[[noreturn]] void Die(const std::string& msg) {
  std::fprintf(stderr, "shlo_runner: %s\n", msg.c_str());
  std::exit(1);
}

const PJRT_Api* g_api = nullptr;

void Check(PJRT_Error* err, const char* what) {
  if (err == nullptr) return;
  PJRT_Error_Message_Args margs;
  std::memset(&margs, 0, sizeof(margs));
  margs.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  margs.error = err;
  g_api->PJRT_Error_Message(&margs);
  std::string msg(margs.message, margs.message_size);
  PJRT_Error_Destroy_Args dargs;
  std::memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  dargs.error = err;
  g_api->PJRT_Error_Destroy(&dargs);
  Die(std::string(what) + ": " + msg);
}

std::string ReadFile(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) Die("cannot read " + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

struct DType {
  PJRT_Buffer_Type type;
  size_t bytes;
};

int64_t ParseInt(const std::string& s, const std::string& what) {
  try {
    size_t pos = 0;
    int64_t v = std::stoll(s, &pos);
    if (pos != s.size()) throw std::invalid_argument(s);
    return v;
  } catch (const std::exception&) {
    Die("malformed integer '" + s + "' in " + what);
  }
}

DType ParseDType(const std::string& s) {
  if (s == "f32") return {PJRT_Buffer_Type_F32, 4};
  if (s == "f64") return {PJRT_Buffer_Type_F64, 8};
  if (s == "f16") return {PJRT_Buffer_Type_F16, 2};
  if (s == "bf16") return {PJRT_Buffer_Type_BF16, 2};
  if (s == "i8") return {PJRT_Buffer_Type_S8, 1};
  if (s == "u8") return {PJRT_Buffer_Type_U8, 1};
  if (s == "i32") return {PJRT_Buffer_Type_S32, 4};
  if (s == "i64") return {PJRT_Buffer_Type_S64, 8};
  if (s == "pred") return {PJRT_Buffer_Type_PRED, 1};
  Die("unsupported dtype " + s);
}

const char* TypeName(PJRT_Buffer_Type t) {
  switch (t) {
    case PJRT_Buffer_Type_F32: return "f32";
    case PJRT_Buffer_Type_F64: return "f64";
    case PJRT_Buffer_Type_F16: return "f16";
    case PJRT_Buffer_Type_BF16: return "bf16";
    case PJRT_Buffer_Type_S8: return "i8";
    case PJRT_Buffer_Type_U8: return "u8";
    case PJRT_Buffer_Type_S32: return "i32";
    case PJRT_Buffer_Type_S64: return "i64";
    case PJRT_Buffer_Type_PRED: return "pred";
    default: return "unknown";
  }
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 5) {
    std::fprintf(stderr,
                 "usage: %s <plugin.so> <module.mlir> "
                 "<compile_options.pb|-> <out_prefix> "
                 "[dtype@d0xd1@file.bin ...]\n",
                 argv[0]);
    return 2;
  }
  const char* plugin_path = argv[1];
  const std::string module = ReadFile(argv[2]);
  std::string options;
  if (std::strcmp(argv[3], "-") != 0) options = ReadFile(argv[3]);
  const std::string out_prefix = argv[4];

  void* lib = dlopen(plugin_path, RTLD_NOW | RTLD_LOCAL);
  if (lib == nullptr) Die(std::string("dlopen: ") + dlerror());
  using GetApiFn = const PJRT_Api* (*)();
  auto get_api = reinterpret_cast<GetApiFn>(dlsym(lib, "GetPjrtApi"));
  if (get_api == nullptr) Die("plugin has no GetPjrtApi symbol");
  g_api = get_api();
  if (g_api == nullptr) Die("GetPjrtApi returned null");
  std::fprintf(stderr, "shlo_runner: plugin PJRT API v%d.%d\n",
               g_api->pjrt_api_version.major_version,
               g_api->pjrt_api_version.minor_version);

  {
    PJRT_Plugin_Initialize_Args init;
    std::memset(&init, 0, sizeof(init));
    init.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
    Check(g_api->PJRT_Plugin_Initialize(&init), "Plugin_Initialize");
  }

  // client-create options from --opt args (strings kept alive in vectors)
  std::vector<std::string> opt_names, opt_strs;
  std::vector<int64_t> opt_ints;
  std::vector<std::pair<size_t, char>> opt_kinds;  // (index, 'i'|'s')
  std::vector<int> input_argv;
  for (int i = 5; i < argc; ++i) {
    if (std::strcmp(argv[i], "--opt") == 0 && i + 1 < argc) {
      std::string kv(argv[++i]);
      size_t eq = kv.find('=');
      if (eq == std::string::npos || kv.size() < eq + 3 ||
          kv[eq + 2] != ':' || (kv[eq + 1] != 'i' && kv[eq + 1] != 's'))
        Die("bad --opt " + kv + " (want name=i:42 or name=s:text)");
      opt_names.push_back(kv.substr(0, eq));
      if (kv[eq + 1] == 'i') {
        opt_kinds.emplace_back(opt_ints.size(), 'i');
        opt_ints.push_back(ParseInt(kv.substr(eq + 3), "--opt " + kv));
      } else {
        opt_kinds.emplace_back(opt_strs.size(), 's');
        opt_strs.push_back(kv.substr(eq + 3));
      }
    } else {
      input_argv.push_back(i);
    }
  }
  std::vector<PJRT_NamedValue> named(opt_names.size());
  for (size_t i = 0; i < opt_names.size(); ++i) {
    std::memset(&named[i], 0, sizeof(named[i]));
    named[i].struct_size = PJRT_NamedValue_STRUCT_SIZE;
    named[i].name = opt_names[i].c_str();
    named[i].name_size = opt_names[i].size();
    if (opt_kinds[i].second == 'i') {
      named[i].type = PJRT_NamedValue_kInt64;
      named[i].int64_value = opt_ints[opt_kinds[i].first];
      named[i].value_size = 1;
    } else {
      const std::string& s = opt_strs[opt_kinds[i].first];
      named[i].type = PJRT_NamedValue_kString;
      named[i].string_value = s.c_str();
      named[i].value_size = s.size();
    }
  }

  PJRT_Client_Create_Args cargs;
  std::memset(&cargs, 0, sizeof(cargs));
  cargs.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  cargs.create_options = named.data();
  cargs.num_options = named.size();
  Check(g_api->PJRT_Client_Create(&cargs), "Client_Create");
  PJRT_Client* client = cargs.client;

  PJRT_Client_AddressableDevices_Args dargs;
  std::memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
  dargs.client = client;
  Check(g_api->PJRT_Client_AddressableDevices(&dargs),
        "AddressableDevices");
  if (dargs.num_addressable_devices == 0) Die("no addressable devices");
  PJRT_Device* device = dargs.addressable_devices[0];

  // ------------------------------------------------------------- compile
  PJRT_Program program;
  std::memset(&program, 0, sizeof(program));
  program.struct_size = PJRT_Program_STRUCT_SIZE;
  program.code = const_cast<char*>(module.data());
  program.code_size = module.size();
  static const char kFormat[] = "mlir";
  program.format = kFormat;
  program.format_size = sizeof(kFormat) - 1;

  PJRT_Client_Compile_Args comp;
  std::memset(&comp, 0, sizeof(comp));
  comp.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
  comp.client = client;
  comp.program = &program;
  comp.compile_options = options.data();
  comp.compile_options_size = options.size();
  Check(g_api->PJRT_Client_Compile(&comp), "Client_Compile");
  PJRT_LoadedExecutable* exec = comp.executable;

  PJRT_LoadedExecutable_GetExecutable_Args gargs;
  std::memset(&gargs, 0, sizeof(gargs));
  gargs.struct_size = PJRT_LoadedExecutable_GetExecutable_Args_STRUCT_SIZE;
  gargs.loaded_executable = exec;
  Check(g_api->PJRT_LoadedExecutable_GetExecutable(&gargs),
        "GetExecutable");
  PJRT_Executable_NumOutputs_Args nargs;
  std::memset(&nargs, 0, sizeof(nargs));
  nargs.struct_size = PJRT_Executable_NumOutputs_Args_STRUCT_SIZE;
  nargs.executable = gargs.executable;
  Check(g_api->PJRT_Executable_NumOutputs(&nargs), "NumOutputs");
  const size_t num_outputs = nargs.num_outputs;

  // ------------------------------------------------- host->device inputs
  std::vector<PJRT_Buffer*> inputs;
  std::vector<std::string> input_bytes;  // keep host data alive
  for (int ia : input_argv) {
    std::string spec(argv[ia]);
    size_t a = spec.find('@');
    size_t b = spec.find('@', a + 1);
    if (a == std::string::npos || b == std::string::npos)
      Die("bad input spec " + spec + " (want dtype@d0xd1@file)");
    DType dt = ParseDType(spec.substr(0, a));
    std::vector<int64_t> dims;
    std::string shape = spec.substr(a + 1, b - a - 1);
    if (shape != "scalar") {
      std::stringstream ss(shape);
      std::string tok;
      while (std::getline(ss, tok, 'x'))
        dims.push_back(ParseInt(tok, "input spec " + spec));
    }
    input_bytes.push_back(ReadFile(spec.substr(b + 1)));
    size_t want = dt.bytes;
    for (int64_t d : dims) want *= static_cast<size_t>(d);
    if (input_bytes.back().size() != want)
      Die("input " + spec + ": file has " +
          std::to_string(input_bytes.back().size()) + " bytes, want " +
          std::to_string(want));

    PJRT_Client_BufferFromHostBuffer_Args bargs;
    std::memset(&bargs, 0, sizeof(bargs));
    bargs.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
    bargs.client = client;
    bargs.data = input_bytes.back().data();
    bargs.type = dt.type;
    bargs.dims = dims.data();
    bargs.num_dims = dims.size();
    bargs.host_buffer_semantics =
        PJRT_HostBufferSemantics_kImmutableUntilTransferCompletes;
    bargs.device = device;
    Check(g_api->PJRT_Client_BufferFromHostBuffer(&bargs),
          "BufferFromHostBuffer");
    if (bargs.done_with_host_buffer != nullptr) {
      PJRT_Event_Await_Args eargs;
      std::memset(&eargs, 0, sizeof(eargs));
      eargs.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
      eargs.event = bargs.done_with_host_buffer;
      Check(g_api->PJRT_Event_Await(&eargs), "Event_Await(h2d)");
      PJRT_Event_Destroy_Args edargs;
      std::memset(&edargs, 0, sizeof(edargs));
      edargs.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
      edargs.event = bargs.done_with_host_buffer;
      g_api->PJRT_Event_Destroy(&edargs);
    }
    inputs.push_back(bargs.buffer);
  }

  // -------------------------------------------------------------- execute
  PJRT_ExecuteOptions opts;
  std::memset(&opts, 0, sizeof(opts));
  opts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;

  std::vector<PJRT_Buffer*> outputs(num_outputs, nullptr);
  PJRT_Buffer** output_list = outputs.data();
  PJRT_Buffer* const* arg_list = inputs.data();
  PJRT_Event* done = nullptr;

  PJRT_LoadedExecutable_Execute_Args eargs;
  std::memset(&eargs, 0, sizeof(eargs));
  eargs.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
  eargs.executable = exec;
  eargs.options = &opts;
  eargs.argument_lists = &arg_list;
  eargs.num_devices = 1;
  eargs.num_args = inputs.size();
  eargs.output_lists = &output_list;
  eargs.device_complete_events = &done;
  eargs.execute_device = device;
  Check(g_api->PJRT_LoadedExecutable_Execute(&eargs), "Execute");
  if (done != nullptr) {
    PJRT_Event_Await_Args aw;
    std::memset(&aw, 0, sizeof(aw));
    aw.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
    aw.event = done;
    Check(g_api->PJRT_Event_Await(&aw), "Event_Await(execute)");
    PJRT_Event_Destroy_Args ed;
    std::memset(&ed, 0, sizeof(ed));
    ed.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
    ed.event = done;
    g_api->PJRT_Event_Destroy(&ed);
  }

  // ------------------------------------------------ device->host outputs
  for (size_t i = 0; i < num_outputs; ++i) {
    PJRT_Buffer_ElementType_Args targs;
    std::memset(&targs, 0, sizeof(targs));
    targs.struct_size = PJRT_Buffer_ElementType_Args_STRUCT_SIZE;
    targs.buffer = outputs[i];
    Check(g_api->PJRT_Buffer_ElementType(&targs), "ElementType");
    PJRT_Buffer_Dimensions_Args shargs;
    std::memset(&shargs, 0, sizeof(shargs));
    shargs.struct_size = PJRT_Buffer_Dimensions_Args_STRUCT_SIZE;
    shargs.buffer = outputs[i];
    Check(g_api->PJRT_Buffer_Dimensions(&shargs), "Dimensions");

    PJRT_Buffer_ToHostBuffer_Args hargs;
    std::memset(&hargs, 0, sizeof(hargs));
    hargs.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    hargs.src = outputs[i];
    Check(g_api->PJRT_Buffer_ToHostBuffer(&hargs), "ToHostBuffer(size)");
    std::vector<char> host(hargs.dst_size);
    hargs.dst = host.data();
    Check(g_api->PJRT_Buffer_ToHostBuffer(&hargs), "ToHostBuffer");
    if (hargs.event != nullptr) {
      PJRT_Event_Await_Args aw;
      std::memset(&aw, 0, sizeof(aw));
      aw.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
      aw.event = hargs.event;
      Check(g_api->PJRT_Event_Await(&aw), "Event_Await(d2h)");
      PJRT_Event_Destroy_Args ed;
      std::memset(&ed, 0, sizeof(ed));
      ed.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
      ed.event = hargs.event;
      g_api->PJRT_Event_Destroy(&ed);
    }

    const std::string stem = out_prefix + "." + std::to_string(i);
    std::ofstream ob(stem + ".bin", std::ios::binary);
    ob.write(host.data(), static_cast<std::streamsize>(host.size()));
    ob.close();
    if (!ob) Die("failed writing " + stem + ".bin");
    std::ofstream om(stem + ".meta");
    om << TypeName(targs.type);
    for (size_t d = 0; d < shargs.num_dims; ++d)
      om << " " << shargs.dims[d];
    om << "\n";
    om.close();
    if (!om) Die("failed writing " + stem + ".meta");
  }
  std::fprintf(stderr, "shlo_runner: wrote %zu output(s)\n", num_outputs);
  return 0;
}
