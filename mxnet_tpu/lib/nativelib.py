"""ctypes binding + on-demand build of the native IO library.

Reference: the reference links dmlc-core/src/recordio.cc and the C++
iterator tier into libmxnet.so at build time (SURVEY.md §2.1).  Here the
library is a single translation unit compiled on first use with the
toolchain in the image (g++ -O3 -shared) and cached next to the sources;
every caller keeps a pure-Python fallback, so a missing compiler degrades
performance, never correctness.  ``mx.runtime.Features()["NATIVE_IO"]``
reports which path is active.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from ..base import env_truthy

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "nativelib.cc")
_SO = os.path.join(_DIR, "libmxnet_tpu_native.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    base = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            "-o", _SO, _SRC]
    # libjpeg powers the threaded decode tier; hosts without it still
    # get the recordio/csv tier (decode falls back to Python/cv2)
    for cmd in (base + ["-ljpeg"], base + ["-DMXNATIVE_NO_JPEG"]):
        try:
            proc = subprocess.run(cmd, capture_output=True, timeout=120)
            if proc.returncode == 0 and os.path.exists(_SO):
                return True
        except (OSError, subprocess.TimeoutExpired):
            return False
    return False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        # '0'/'' = off, like every other boolean knob
        if env_truthy("MXNET_TPU_DISABLE_NATIVE"):
            return None
        stale = (not os.path.exists(_SO) or
                 os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if stale and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        if lib.mxnative_abi_version() != 1:
            return None
        lib.mxrec_open.restype = ctypes.c_void_p
        lib.mxrec_open.argtypes = [ctypes.c_char_p]
        lib.mxrec_close.argtypes = [ctypes.c_void_p]
        lib.mxrec_index.restype = ctypes.c_int64
        lib.mxrec_index.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_int64),
                                    ctypes.c_int64]
        lib.mxrec_read_at.restype = ctypes.c_int64
        lib.mxrec_read_at.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      ctypes.c_char_p, ctypes.c_int64]
        lib.mxrec_create.restype = ctypes.c_void_p
        lib.mxrec_create.argtypes = [ctypes.c_char_p]
        lib.mxrec_write.restype = ctypes.c_int64
        lib.mxrec_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int64]
        lib.mxcsv_shape.restype = ctypes.c_int64
        lib.mxcsv_shape.argtypes = [ctypes.c_char_p,
                                    ctypes.POINTER(ctypes.c_int64)]
        lib.mxcsv_parse.restype = ctypes.c_int64
        lib.mxcsv_parse.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            ctypes.c_int64]
        if lib.mxnative_has_jpeg():
            lib.mxjpeg_decode_batch.restype = ctypes.c_int64
            lib.mxjpeg_decode_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                ctypes.c_int64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
                ctypes.c_int64]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# high-level wrappers (all raise RuntimeError when the lib is unavailable;
# callers gate on available())
# ---------------------------------------------------------------------------

class NativeRecordReader:
    """Random-access record reader over the C++ scanner."""

    def __init__(self, path: str):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.mxrec_open(path.encode())
        if not self._h:
            raise OSError(f"cannot open {path!r}")

    def close(self):
        if self._h:
            self._lib.mxrec_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def index(self) -> np.ndarray:
        """Byte offsets of every logical record (the .idx-less scan)."""
        count = self._lib.mxrec_index(self._h, None, 0)
        if count < 0:
            raise IOError("corrupt record file")
        offsets = np.zeros(count, np.int64)
        got = self._lib.mxrec_index(
            self._h,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), count)
        if got != count:
            raise IOError("record file changed during scan")
        return offsets

    def read_at(self, offset: int) -> bytes:
        need = self._lib.mxrec_read_at(self._h, offset, None, 0)
        if need < 0:
            raise IOError(f"corrupt record at offset {offset}")
        buf = ctypes.create_string_buffer(need)
        got = self._lib.mxrec_read_at(self._h, offset, buf, need)
        if got != need:
            raise IOError(f"short read at offset {offset}")
        return buf.raw


class NativeRecordWriter:
    def __init__(self, path: str):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.mxrec_create(path.encode())
        if not self._h:
            raise OSError(f"cannot create {path!r}")

    def write(self, payload: bytes) -> int:
        n = self._lib.mxrec_write(self._h, payload, len(payload))
        if n < 0:
            raise IOError("record write failed")
        return n

    def close(self):
        if self._h:
            self._lib.mxrec_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def jpeg_available() -> bool:
    lib = _load()
    return lib is not None and bool(lib.mxnative_has_jpeg())


def decode_jpeg_batch(bufs, resize_min, out_h, out_w, cy_frac, cx_frac,
                      mirror, n_threads):
    """Decode a batch of JPEG byte strings on native OS threads.

    Returns (batch (n, 3, out_h, out_w) uint8, status (n,) uint8 —
    0 = decoded, nonzero = that image needs the Python fallback).
    Augmentation randomness (crop fractions, mirror flags) is supplied
    by the caller so the seeded-RNG contract is unchanged.
    """
    lib = _load()
    if lib is None or not lib.mxnative_has_jpeg():
        raise RuntimeError("native JPEG tier unavailable")
    n = len(bufs)
    arr = (ctypes.c_char_p * n)(*bufs)
    lens = np.array([len(b) for b in bufs], np.int64)
    out = np.empty((n, 3, out_h, out_w), np.uint8)
    status = np.ones(n, np.uint8)
    lib.mxjpeg_decode_batch(
        ctypes.cast(arr, ctypes.POINTER(ctypes.c_char_p)), lens, n,
        int(resize_min or 0), int(out_h), int(out_w),
        np.ascontiguousarray(cy_frac, np.float32),
        np.ascontiguousarray(cx_frac, np.float32),
        np.ascontiguousarray(mirror, np.uint8), out, status,
        int(n_threads))
    return out, status


def csv_load(path: str) -> np.ndarray:
    """Parse a numeric CSV into a (rows, cols) float32 array."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n_vals = ctypes.c_int64()
    rows = lib.mxcsv_shape(path.encode(), ctypes.byref(n_vals))
    if rows < 0:
        raise OSError(f"cannot open {path!r}")
    out = np.empty(n_vals.value, np.float32)
    got = lib.mxcsv_parse(path.encode(), out, n_vals.value)
    if got == -3:
        raise ValueError(
            f"non-numeric field in {path!r} (header line?) — "
            f"CSVIter expects numeric-only files")
    if got != n_vals.value:
        raise IOError(f"csv parse mismatch in {path!r}")
    if rows and n_vals.value % rows:
        raise IOError(f"ragged csv {path!r}")
    return out.reshape(rows, n_vals.value // rows) if rows else \
        out.reshape(0, 0)
