"""Dynamic custom-operator library loading.

Reference surface: ``mx.library.load`` / ``MXLoadLib``
(``python/mxnet/library.py`` + ``src/initialize.cc`` dynamic custom-op
lib loading, backed by the ``lib_api.h`` plugin ABI in
``src/lib_api.h``) — SURVEY.md §2.1 Initialization row.  Upstream lets
users ship compiled operator libraries (.so) that register new ops into
the runtime without rebuilding MXNet.

TPU-native redesign: compute stays on XLA, so a plugin op is a *host*
kernel — exactly the role of the reference's CPU-only ``lib_api.h``
libraries.  A plugin .so exports a small C ABI (below); ``load()`` binds
it with ctypes and registers each exported op as a ``CustomOpProp``, so
plugin ops get the full Custom machinery: eager NDArray calls, autograd
(when the lib exports a backward), and ``hybridize()``/``jit`` via
``jax.pure_callback`` — reachable as ``mx.nd.Custom(x, op_type=name)``
and as generated ``mx.nd.<name>`` frontends.

Plugin C ABI (version 1, float32, single-output):

.. code-block:: c

    int         mxlib_abi_version(void);            // must return 1
    int         mxlib_num_ops(void);
    const char* mxlib_op_name(int op);
    int         mxlib_op_num_inputs(int op);
    int         mxlib_op_has_backward(int op);
    // out_shape has room for 8 dims; return out ndim, or -1 on error
    int  mxlib_op_infer_shape(int op, int n_in, const int64_t* shapes,
                              const int* ndims, int64_t* out_shape);
    // flat float32 buffers; shapes as in infer_shape; 0 = ok
    int  mxlib_op_forward(int op, int n_in, const float** ins,
                          const int64_t* shapes, const int* ndims,
                          float* out, const int64_t* out_shape,
                          int out_ndim);
    // in_grads[i] has input i's shape; 0 = ok
    int  mxlib_op_backward(int op, int n_in, const float* out_grad,
                           const float** ins, const int64_t* shapes,
                           const int* ndims, float** in_grads);
"""
from __future__ import annotations

import ctypes
import os
from typing import Dict, List

import numpy as np

from .base import MXNetError

__all__ = ["load", "loaded_libraries"]

_LOADED: Dict[str, "_PluginLib"] = {}

_MAX_DIMS = 8


class _PluginLib:
    """ctypes binding of one plugin .so."""

    def __init__(self, path: str):
        self.path = path
        self.cdll = ctypes.CDLL(path)
        c = self.cdll
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int)
        fpp = ctypes.POINTER(ctypes.POINTER(ctypes.c_float))
        fp = ctypes.POINTER(ctypes.c_float)

        try:
            c.mxlib_abi_version.restype = ctypes.c_int
            abi = c.mxlib_abi_version()
        except AttributeError:
            raise MXNetError(
                f"{path} is not an mxnet_tpu op library "
                f"(missing mxlib_abi_version)")
        if abi != 1:
            raise MXNetError(
                f"{path}: plugin ABI version {abi} unsupported (want 1)")

        c.mxlib_num_ops.restype = ctypes.c_int
        c.mxlib_op_name.restype = ctypes.c_char_p
        c.mxlib_op_name.argtypes = [ctypes.c_int]
        c.mxlib_op_num_inputs.restype = ctypes.c_int
        c.mxlib_op_num_inputs.argtypes = [ctypes.c_int]
        c.mxlib_op_has_backward.restype = ctypes.c_int
        c.mxlib_op_has_backward.argtypes = [ctypes.c_int]
        c.mxlib_op_infer_shape.restype = ctypes.c_int
        c.mxlib_op_infer_shape.argtypes = [
            ctypes.c_int, ctypes.c_int, i64p, i32p, i64p]
        c.mxlib_op_forward.restype = ctypes.c_int
        c.mxlib_op_forward.argtypes = [
            ctypes.c_int, ctypes.c_int, fpp, i64p, i32p, fp, i64p,
            ctypes.c_int]
        c.mxlib_op_backward.restype = ctypes.c_int
        c.mxlib_op_backward.argtypes = [
            ctypes.c_int, ctypes.c_int, fp, fpp, i64p, i32p, fpp]

        self.op_names: List[str] = []
        for i in range(c.mxlib_num_ops()):
            self.op_names.append(c.mxlib_op_name(i).decode("utf-8"))

    # -- marshalling ------------------------------------------------------
    @staticmethod
    def _pack_shapes(shapes):
        flat = []
        ndims = []
        for s in shapes:
            if len(s) > _MAX_DIMS:
                raise MXNetError(f"plugin ops support <= {_MAX_DIMS} dims, "
                                 f"got shape {tuple(s)}")
            flat.extend(int(d) for d in s)
            ndims.append(len(s))
        c_flat = (ctypes.c_int64 * max(1, len(flat)))(*flat)
        c_ndims = (ctypes.c_int * max(1, len(ndims)))(*ndims)
        return c_flat, c_ndims

    def infer_shape(self, op_idx, in_shapes):
        c_flat, c_ndims = self._pack_shapes(in_shapes)
        out_shape = (ctypes.c_int64 * _MAX_DIMS)()
        ndim = self.cdll.mxlib_op_infer_shape(
            op_idx, len(in_shapes), c_flat, c_ndims, out_shape)
        if ndim < 0:
            raise MXNetError(
                f"{self.op_names[op_idx]}: infer_shape failed for "
                f"{[tuple(s) for s in in_shapes]}")
        return [int(out_shape[i]) for i in range(ndim)]

    def forward(self, op_idx, arrays, out_shape):
        arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
        c_flat, c_ndims = self._pack_shapes([a.shape for a in arrays])
        ins = (ctypes.POINTER(ctypes.c_float) * len(arrays))(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        out = np.zeros(out_shape, np.float32)
        c_oshape = (ctypes.c_int64 * max(1, len(out_shape)))(
            *[int(d) for d in out_shape])
        rc = self.cdll.mxlib_op_forward(
            op_idx, len(arrays), ins, c_flat, c_ndims,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            c_oshape, len(out_shape))
        if rc != 0:
            raise MXNetError(
                f"{self.op_names[op_idx]}: forward failed (rc={rc})")
        return out

    def backward(self, op_idx, out_grad, arrays):
        arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
        out_grad = np.ascontiguousarray(out_grad, np.float32)
        c_flat, c_ndims = self._pack_shapes([a.shape for a in arrays])
        ins = (ctypes.POINTER(ctypes.c_float) * len(arrays))(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        grads = [np.zeros(a.shape, np.float32) for a in arrays]
        gptrs = (ctypes.POINTER(ctypes.c_float) * len(arrays))(
            *[g.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for g in grads])
        rc = self.cdll.mxlib_op_backward(
            op_idx, len(arrays),
            out_grad.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ins, c_flat, c_ndims, gptrs)
        if rc != 0:
            raise MXNetError(
                f"{self.op_names[op_idx]}: backward failed (rc={rc})")
        return grads


def _make_prop_class(lib: _PluginLib, op_idx: int, name: str):
    """Build a CustomOpProp subclass delegating to the plugin kernels."""
    from . import operator as op_mod

    n_in = lib.cdll.mxlib_op_num_inputs(op_idx)
    has_bwd = bool(lib.cdll.mxlib_op_has_backward(op_idx))

    class _PluginOp(op_mod.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            ins = [d.asnumpy() for d in in_data]
            out = lib.forward(op_idx, ins, out_data[0].shape)
            self.assign(out_data[0], req[0], out)

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            if not has_bwd:
                raise MXNetError(
                    f"plugin op {name!r} exports no backward")
            grads = lib.backward(op_idx, out_grad[0].asnumpy(),
                                 [d.asnumpy() for d in in_data])
            for dst, r, g in zip(in_grad, req, grads):
                self.assign(dst, r, g)

    class _PluginProp(op_mod.CustomOpProp):
        def __init__(self, **kwargs):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return [f"data{i}" for i in range(n_in)] if n_in != 1 \
                else ["data"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            out = lib.infer_shape(op_idx, in_shape)
            return in_shape, [out], []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            return _PluginOp()

    _PluginProp.__name__ = f"PluginProp_{name}"
    return _PluginProp


def _attach_frontend(name: str) -> bool:
    """Expose the plugin op as mx.nd.<name>(...) like MXLoadLib does.

    A plugin op whose name collides with an existing nd/sym attribute
    (e.g. a built-in operator) does NOT replace it — silently rerouting
    ``nd.dot`` through a host-callback CustomOp would corrupt every
    subsequent caller.  The op stays reachable as
    ``nd.Custom(..., op_type=name)``; returns False on collision.
    """
    import logging
    from . import ndarray as nd_mod
    from . import symbol as sym_mod

    if any(hasattr(m, name) for m in (nd_mod, nd_mod.op, sym_mod,
                                      sym_mod.op)):
        logging.getLogger("mxnet_tpu").warning(
            "library.load: plugin op %r collides with an existing "
            "operator; keeping the built-in — call it via "
            "nd.Custom(..., op_type=%r)", name, name)
        return False

    def frontend(*data, **kwargs):
        return nd_mod.Custom(*data, op_type=name, **kwargs)

    def sym_frontend(*data, **kwargs):
        return sym_mod.Custom(*data, op_type=name, **kwargs)

    frontend.__name__ = name
    frontend.__doc__ = f"Plugin operator {name!r} (loaded via " \
                       f"mx.library.load)."
    for mod, fn in ((nd_mod, frontend), (nd_mod.op, frontend),
                    (sym_mod, sym_frontend), (sym_mod.op, sym_frontend)):
        setattr(mod, name, fn)
    return True


def load(path, verbose=True):
    """Load an operator library (reference: ``mx.library.load`` →
    ``MXLoadLib``).  Registers every exported op; returns the list of
    op names registered."""
    from . import operator as op_mod

    path = os.path.abspath(path)
    if not os.path.exists(path):
        raise MXNetError(f"library not found: {path}")
    if path in _LOADED:
        return list(_LOADED[path].op_names)

    lib = _PluginLib(path)
    for idx, name in enumerate(lib.op_names):
        prop_cls = _make_prop_class(lib, idx, name)
        op_mod.register(name)(prop_cls)
        _attach_frontend(name)
        if verbose:
            import logging
            logging.getLogger("mxnet_tpu").info(
                "library.load: registered op %r from %s", name, path)
    _LOADED[path] = lib
    return list(lib.op_names)


def loaded_libraries():
    """Map of loaded library path → op-name list."""
    return {p: list(l.op_names) for p, l in _LOADED.items()}
