"""Optimizer base + implementations (reference: python/mxnet/optimizer/optimizer.py)."""
from __future__ import annotations

import math
import pickle

import numpy as np

from ..base import MXNetError, Registry
from .. import ndarray as nd
from ..ndarray import NDArray

_REG = Registry("optimizer")


def register(klass):
    _REG.register(klass.__name__.lower(), klass, override=True)
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    klass = _REG.find(name.lower())
    if klass is None:
        raise MXNetError(f"unknown optimizer {name!r}; "
                         f"known: {_REG.list_names()}")
    return klass(**kwargs)


class Optimizer:
    """Base optimizer (reference: Optimizer).

    Subclasses implement ``create_state(index, weight)`` and
    ``update(index, weight, grad, state)``; updates route through the fused
    ops so they're single compiled programs.
    """

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 multi_precision=False, param_dict=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.num_update = begin_num_update
        self.begin_num_update = begin_num_update
        self._index_update_count = {}
        # one Trainer-shared optimizer drives updaters on several device
        # copies; per-device t counters keep Adam-style bias correction
        # from double-advancing (reference: Optimizer._set_current_context)
        self._all_index_update_counts = {0: self._index_update_count}
        self.idx2name = param_idx2name or {}
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}

    # ------------------------------------------------------------- lr/wd
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("cannot set lr directly when lr_scheduler is "
                             "active")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _set_current_context(self, device_id):
        """Switch to ``device_id``'s update-count table (reference:
        Optimizer._set_current_context)."""
        if device_id not in self._all_index_update_counts:
            self._all_index_update_counts[device_id] = {}
        self._index_update_count = self._all_index_update_counts[device_id]

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self.num_update,
                              self._index_update_count[index])

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) \
            if self.lr_scheduler is not None else self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # --------------------------------------------------------------- state
    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        if self.multi_precision and str(weight.dtype) in ("float16",
                                                          "bfloat16"):
            w32 = weight.astype("float32")
            return (w32, self.create_state(index, w32))
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def _is_mp_state(self, weight, state):
        return (self.multi_precision
                and str(weight.dtype) in ("float16", "bfloat16")
                and isinstance(state, tuple) and len(state) == 2
                and isinstance(state[0], NDArray)
                and state[0].shape == weight.shape)

    def update_multi_precision(self, index, weight, grad, state):
        """Generic fp16/bf16 path: update the fp32 master copy with the
        inner state, then cast back (reference: update_multi_precision).
        Optimizers with fused mp ops (SGD) override this."""
        if self._is_mp_state(weight, state):
            w32, base_state = state
            self.update(index, w32, grad.astype("float32"), base_state)
            weight._set_data(w32._data.astype(weight._data.dtype))
        else:
            self.update(index, weight, grad, state)

    # ------------------------------------------------------- fused whole-model
    # step (reference: the multi-tensor ops multi_sgd_update /
    # multi_mp_sgd_mom_update + Trainer MXNET_OPTIMIZER_AGGREGATION_SIZE).
    # On TPU one dispatch per parameter is the eager path's dominant cost, so
    # optimizers that can express their update as a pure per-param kernel
    # opt into a single XLA program covering EVERY parameter: Trainer traces
    # `_fused_one` over all (w, g, state) triples at once.  Step-varying
    # hypers (t, lr, wd, rescale_grad) arrive as traced scalars so the
    # program compiles once and never retraces.
    fused = False

    def _fused_key(self):
        """Static hypers baked into the fused program (cache key part)."""
        return (self.clip_gradient, self.multi_precision)

    def _fused_one(self, w, g, state, t, lr, wd, rescale):
        """Pure kernel: one param's update on raw jax arrays, built from
        the same ops/optimizer_ops.py functions the per-param path runs
        (one source of truth for the update math).  ``state`` mirrors
        create_state(_multi_precision)'s structure with NDArrays replaced
        by arrays.  Step-varying hypers arrive as traced scalars.
        Returns (new_w, new_state)."""
        raise NotImplementedError

    # --------------------------------------------------------- serialization
    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("param_dict", None)
        return d

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.param_dict = {}


def _apply(opname, arrays, **kwargs):
    """Run a fused optimizer op, writing the weight (and states) back."""
    out = nd.invoke_by_name(opname, arrays, kwargs)
    return out


def _rsp_rows(grad):
    """(row_indices, row_values) if grad is RowSparse, else None."""
    from ..ndarray.sparse import RowSparseNDArray
    if isinstance(grad, RowSparseNDArray):
        return (grad._components["indices"].astype("int32"),
                grad._components["data"])
    return None


@register
class SGD(Optimizer):
    """SGD w/ momentum (reference: SGD → sgd_update/sgd_mom_update)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        rows = _rsp_rows(grad) if not isinstance(state, tuple) else None
        if rows is not None and self.lazy_update:
            # lazy row-sparse update: touch only stored rows (reference:
            # sgd_update kRowSparseStorage path).  One XLA gather+scatter.
            from ..ops.optimizer_ops import _prep_grad
            idx, gvals = rows
            w = weight._data
            wr = w[idx]
            g = _prep_grad(gvals.astype(w.dtype), self.rescale_grad,
                           self.clip_gradient, wd, wr)
            if state is None:
                new_rows = wr - lr * g
            else:
                m = state._data
                mr = self.momentum * m[idx] - lr * g
                state._set_data(m.at[idx].set(mr))
                new_rows = wr + mr
            weight._set_data(w.at[idx].set(new_rows))
            return
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient or -1.0)
        if isinstance(state, tuple):  # multi-precision
            w32, mom = state
            if mom is None:
                new_w, new_w32 = _apply("mp_sgd_update",
                                        [weight, grad, w32], **kw)
            else:
                new_w, new_m, new_w32 = _apply(
                    "mp_sgd_mom_update", [weight, grad, mom, w32],
                    momentum=self.momentum, **kw)
                mom._set_data(new_m._data)
            weight._set_data(new_w._data)
            w32._set_data(new_w32._data)
            return
        if state is None:
            new_w = _apply("sgd_update", [weight, grad], **kw)
            weight._set_data(new_w._data)
        else:
            new_w, new_m = _apply("sgd_mom_update", [weight, grad, state],
                                  momentum=self.momentum, **kw)
            weight._set_data(new_w._data)
            state._set_data(new_m._data)

    def update_multi_precision(self, index, weight, grad, state):
        self.update(index, weight, grad, state)

    fused = True

    def _fused_key(self):
        return super()._fused_key() + (self.momentum,)

    def _fused_one(self, w, g, state, t, lr, wd, rescale):
        from ..ops import optimizer_ops as oo
        clip = self.clip_gradient or -1.0
        kw = dict(lr=lr, wd=wd, rescale_grad=rescale, clip_gradient=clip)
        if isinstance(state, tuple):            # multi-precision (w32, mom)
            w32, mom = state
            if mom is None:
                wn, w32n = oo.mp_sgd_update(w, g, w32, **kw)
                return wn, (w32n, None)
            wn, mn, w32n = oo.mp_sgd_mom_update(w, g, mom, w32,
                                                momentum=self.momentum, **kw)
            return wn, (w32n, mn)
        if state is None:
            return oo.sgd_update(w, g, **kw), None
        wn, mn = oo.sgd_mom_update(w, g, state, momentum=self.momentum, **kw)
        return wn, mn


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference: NAG → nag_mom_update)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        new_w, new_m = _apply(
            "nag_mom_update", [weight, grad, state],
            lr=self._get_lr(index), wd=self._get_wd(index),
            momentum=self.momentum, rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient or -1.0)
        weight._set_data(new_w._data)
        state._set_data(new_m._data)


@register
class Adam(Optimizer):
    """Adam (reference: Adam → adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        lr *= math.sqrt(1. - self.beta2 ** t) / (1. - self.beta1 ** t)
        mean, var = state
        rows = _rsp_rows(grad)
        if rows is not None and self.lazy_update:
            # lazy adam (reference: adam_update kRowSparseStorage): only
            # stored rows advance their moments — matches reference
            # semantics where untouched rows' m/v stay frozen
            from ..ops.optimizer_ops import _prep_grad
            idx, gvals = rows
            w = weight._data
            g = _prep_grad(gvals.astype(w.dtype), self.rescale_grad,
                           self.clip_gradient, self._get_wd(index), w[idx])
            import jax.numpy as jnp
            m, v = mean._data, var._data
            mr = self.beta1 * m[idx] + (1 - self.beta1) * g
            vr = self.beta2 * v[idx] + (1 - self.beta2) * g * g
            mean._set_data(m.at[idx].set(mr))
            var._set_data(v.at[idx].set(vr))
            new_rows = w[idx] - lr * mr / (jnp.sqrt(vr) + self.epsilon)
            weight._set_data(w.at[idx].set(new_rows))
            return
        new_w, new_m, new_v = _apply(
            "adam_update", [weight, grad, mean, var],
            lr=lr, beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
            wd=self._get_wd(index), rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient or -1.0)
        weight._set_data(new_w._data)
        mean._set_data(new_m._data)
        var._set_data(new_v._data)

    fused = True

    def _fused_key(self):
        return super()._fused_key() + (self.beta1, self.beta2, self.epsilon)

    def _fused_one(self, w, g, state, t, lr, wd, rescale):
        import jax.numpy as jnp
        from ..ops import optimizer_ops as oo
        mp = (isinstance(state, tuple) and len(state) == 2
              and isinstance(state[1], tuple))
        if mp:
            w32, (m, v) = state
            weff, geff = w32, g.astype(jnp.float32)
        else:
            m, v = state
            weff, geff = w, g
        lr_t = lr * jnp.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        wn, mn, vn = oo.adam_update(
            weff, geff, m, v, lr=lr_t, beta1=self.beta1, beta2=self.beta2,
            epsilon=self.epsilon, wd=wd, rescale_grad=rescale,
            clip_gradient=self.clip_gradient or -1.0)
        if mp:
            return wn.astype(w.dtype), (wn, (mn, vn))
        return wn, (mn, vn)


@register
class AdamW(Optimizer):
    """AdamW: decoupled weight decay (reference: contrib AdamW →
    adamw_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        # bias correction applies only to the gradient term; decoupled decay
        # is scaled by lr alone: w -= eta*(lr*m/(sqrt(v)+eps) + wd*w) with
        # eta=lr, lr=corr gives  lr*corr*m_hat + lr*wd*w
        corr = math.sqrt(1. - self.beta2 ** t) / (1. - self.beta1 ** t)
        mean, var = state
        rescale = nd.full((1,), self.rescale_grad, ctx=weight.context)
        new_w, new_m, new_v = _apply(
            "adamw_update", [weight, grad, mean, var, rescale],
            lr=corr, eta=lr, beta1=self.beta1, beta2=self.beta2,
            epsilon=self.epsilon, wd=self._get_wd(index),
            clip_gradient=self.clip_gradient or -1.0)
        weight._set_data(new_w._data)
        mean._set_data(new_m._data)
        var._set_data(new_v._data)

    fused = True

    def _fused_key(self):
        return super()._fused_key() + (self.beta1, self.beta2, self.epsilon)

    def _fused_one(self, w, g, state, t, lr, wd, rescale):
        import jax.numpy as jnp
        from ..ops import optimizer_ops as oo
        mp = (isinstance(state, tuple) and len(state) == 2
              and isinstance(state[1], tuple))
        if mp:
            w32, (m, v) = state
            weff, geff = w32, g.astype(jnp.float32)
        else:
            m, v = state
            weff, geff = w, g
        # decoupled decay scaled by lr only; bias correction on grad term
        # (same lr=corr / eta=lr split the per-param path feeds the op)
        corr = jnp.sqrt(1.0 - self.beta2 ** t) / (1.0 - self.beta1 ** t)
        wn, mn, vn = oo.adamw_update(
            weff, geff, m, v, rescale, lr=corr, eta=lr, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon, wd=wd,
            clip_gradient=self.clip_gradient or -1.0)
        if mp:
            return wn.astype(w.dtype), (wn, (mn, vn))
        return wn, (mn, vn)


@register
class LAMB(Optimizer):
    """LAMB: layer-wise adaptive large-batch optimizer (reference:
    lamb_update_phase1/2)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        mean, var = state
        g = _apply("lamb_update_phase1", [weight, grad, mean, var],
                   beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
                   t=t, bias_correction=self.bias_correction,
                   wd=self._get_wd(index), rescale_grad=self.rescale_grad,
                   clip_gradient=self.clip_gradient or -1.0)
        new_m, new_v = _apply("lamb_update_states",
                              [weight, grad, mean, var],
                              beta1=self.beta1, beta2=self.beta2,
                              rescale_grad=self.rescale_grad)
        r1 = weight.norm()
        r2 = g.norm()
        new_w = _apply("lamb_update_phase2", [weight, g, r1, r2],
                       lr=self._get_lr(index),
                       lower_bound=self.lower_bound or -1.0,
                       upper_bound=self.upper_bound or -1.0)
        weight._set_data(new_w._data)
        mean._set_data(new_m._data)
        var._set_data(new_v._data)


@register
class RMSProp(Optimizer):
    """RMSProp (reference: RMSProp → rmsprop_update/rmspropalex_update)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.epsilon = epsilon
        self.centered = centered
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        zeros = lambda: nd.zeros(weight.shape, ctx=weight.context,
                                 dtype=weight.dtype)
        if self.centered:
            return (zeros(), zeros(), zeros())
        return (zeros(),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = dict(lr=lr, wd=wd, gamma1=self.gamma1, epsilon=self.epsilon,
                  rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient or -1.0)
        if self.centered:
            n, g_acc, delta = state
            new_w, new_n, new_g, new_d = _apply(
                "rmspropalex_update", [weight, grad, n, g_acc, delta],
                gamma2=self.gamma2,
                clip_weights=self.clip_weights or -1.0, **kw)
            weight._set_data(new_w._data)
            n._set_data(new_n._data)
            g_acc._set_data(new_g._data)
            delta._set_data(new_d._data)
        else:
            (n,) = state
            new_w, new_n = _apply("rmsprop_update", [weight, grad, n], **kw)
            weight._set_data(new_w._data)
            n._set_data(new_n._data)


@register
class AdaGrad(Optimizer):
    def __init__(self, learning_rate=0.01, eps=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.op.clip(grad, a_min=-self.clip_gradient,
                              a_max=self.clip_gradient)
        hist = state + grad * grad
        state._set_data(hist._data)
        up = grad / (hist.sqrt() + self.float_stable_eps) + wd * weight
        weight._set_data((weight - lr * up)._data)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.op.clip(grad, a_min=-self.clip_gradient,
                              a_max=self.clip_gradient)
        acc_g, acc_delta = state
        new_acc_g = self.rho * acc_g + (1. - self.rho) * grad * grad
        delta = ((acc_delta + self.epsilon).sqrt()
                 / (new_acc_g + self.epsilon).sqrt()) * grad
        new_acc_delta = self.rho * acc_delta + (1. - self.rho) * delta * delta
        acc_g._set_data(new_acc_g._data)
        acc_delta._set_data(new_acc_delta._data)
        weight._set_data((weight - delta - wd * weight)._data)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context,
                         dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        z, n = state
        new_w, new_z, new_n = _apply(
            "ftrl_update", [weight, grad, z, n],
            lr=self._get_lr(index), lamda1=self.lamda1, beta=self.beta,
            wd=self._get_wd(index), rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient or -1.0)
        weight._set_data(new_w._data)
        z._set_data(new_z._data)
        n._set_data(new_n._data)


@register
class SignSGD(Optimizer):
    def __init__(self, learning_rate=0.01, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        new_w = _apply("signsgd_update", [weight, grad],
                       lr=self._get_lr(index), wd=self._get_wd(index),
                       rescale_grad=self.rescale_grad,
                       clip_gradient=self.clip_gradient or -1.0)
        weight._set_data(new_w._data)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        new_w, new_m = _apply(
            "signum_update", [weight, grad, state],
            lr=self._get_lr(index), momentum=self.momentum,
            wd=self._get_wd(index), wd_lh=self.wd_lh,
            rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient or -1.0)
        weight._set_data(new_w._data)
        state._set_data(new_m._data)


@register
class LARS(Optimizer):
    """Layer-wise adaptive rate scaling on top of momentum SGD
    (reference: contrib multi_lars + SGD)."""

    def __init__(self, momentum=0.0, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        w_norm = float(weight.norm().asscalar())
        g_norm = float((grad * self.rescale_grad).norm().asscalar())
        if w_norm > 0 and g_norm > 0:
            lr *= self.eta * w_norm / (g_norm + wd * w_norm + self.epsilon)
        kw = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                  clip_gradient=self.clip_gradient or -1.0)
        if state is None:
            new_w = _apply("sgd_update", [weight, grad], **kw)
            weight._set_data(new_w._data)
        else:
            new_w, new_m = _apply("sgd_mom_update", [weight, grad, state],
                                  momentum=self.momentum, **kw)
            weight._set_data(new_w._data)
            state._set_data(new_m._data)


@register
class Test(Optimizer):
    """Trivial optimizer used by unit tests (reference: opt.Test)."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight._set_data((weight - self.rescale_grad * grad)._data)


class Updater:
    """Per-key state wrapper used by kvstore/Module (reference:
    get_updater)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def get_states(self, dump_optimizer=False):
        payload = {k: _states_to_np(v) for k, v in self.states.items()}
        return pickle.dumps((payload, self.optimizer)
                            if dump_optimizer else payload)

    def set_states(self, states):
        data = pickle.loads(states)
        if isinstance(data, tuple):
            payload, self.optimizer = data
        else:
            payload = data
        self.states = {k: _states_from_np(v) for k, v in payload.items()}


def _states_to_np(state):
    if state is None:
        return None
    if isinstance(state, (list, tuple)):
        return tuple(_states_to_np(s) for s in state)
    return state.asnumpy()


def _states_from_np(state):
    if state is None:
        return None
    if isinstance(state, tuple):
        return tuple(_states_from_np(s) for s in state)
    return nd.array(state)


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
