"""Optimizers (reference: python/mxnet/optimizer/).

Each optimizer delegates its math to the fused update ops in
``ops/optimizer_ops.py`` (reference: src/operator/optimizer_op.cc) so the
update is one XLA program per parameter; under the pjit training path the
same pure functions fuse straight into the compiled step.
"""
from .optimizer import (Optimizer, SGD, NAG, Adam, AdamW, LAMB, RMSProp,
                        AdaGrad, AdaDelta, Ftrl, Signum, SignSGD, LARS,
                        Updater, create, register, get_updater, Test)

__all__ = ["Optimizer", "SGD", "NAG", "Adam", "AdamW", "LAMB", "RMSProp",
           "AdaGrad", "AdaDelta", "Ftrl", "Signum", "SignSGD", "LARS",
           "Updater", "create", "register", "get_updater", "Test"]
