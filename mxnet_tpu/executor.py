"""Symbolic executor: Bind/SimpleBind over one compiled XLA program.

Reference surface being re-created: ``src/executor/graph_executor.cc``
(``GraphExecutor::Bind/SimpleBind/Forward/Backward``) and
``python/mxnet/executor.py`` (SURVEY.md 2.1 "Symbolic executor", 3.5).

TPU-native redesign: the reference walks an nnvm graph and pushes one engine
op per node, with a memory-planning pass (PlanMemory) assigning storage.
Here the whole graph is *one* ``jax.jit``-compiled program — XLA performs
fusion, scheduling and buffer assignment, which subsumes the nnvm pass
pipeline.  Backward is the ``jax.vjp`` of the same interpreted graph,
compiled jointly so XLA shares forward work between fwd and bwd.

Compile caching: one compiled program per (shape, dtype, train) signature —
the executor is re-usable across batches like the reference's
(re)allocated executor, and ``num_compiles`` exposes the trace count so
bucketing policies (module/bucketing_module.py) can bound recompiles.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["Executor"]


class Executor:
    """Executes a Symbol graph with bound argument/aux arrays.

    Parameters mirror ``Symbol.bind`` (reference: MXExecutorBindEX):

    args       : dict name->NDArray, or list in ``list_arguments()`` order
    args_grad  : same container type; receives gradients after backward()
    grad_req   : 'write' | 'add' | 'null', or dict/list per-argument
    aux_states : dict/list for auxiliary (non-differentiable) states
    """

    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx
        # reference manual model parallelism (AttrScope ctx_group +
        # Bind(group2ctx)): accepted for source compatibility; placement
        # is superseded by GSPMD sharding over one logical memory space,
        # so groups are retained as metadata, not device pins
        self._group2ctx = dict(group2ctx or {})
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        self.arg_dict: Dict[str, NDArray] = _as_dict(args, arg_names, "args")
        self.aux_dict: Dict[str, NDArray] = _as_dict(
            aux_states or {}, aux_names, "aux_states")
        missing = [n for n in arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError(f"bind: missing arguments {missing}")

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in arg_names}

        self.grad_dict: Dict[str, NDArray] = {}
        if args_grad is not None:
            self.grad_dict = _as_dict(args_grad, arg_names, "args_grad")
        for n, req in self._grad_req.items():
            if req not in ("write", "add", "null"):
                raise MXNetError(f"invalid grad_req {req!r} for {n!r}")
            if req != "null" and n not in self.grad_dict:
                self.grad_dict[n] = nd.zeros_like(self.arg_dict[n])

        self.outputs: List[NDArray] = []
        self._last_feed = None
        self._is_train = False
        # compile caches keyed on (shapes, dtypes) signature
        self._fwd_cache: Dict[tuple, object] = {}
        self._bwd_cache: Dict[tuple, object] = {}
        self.num_compiles = 0

    # ------------------------------------------------------------ properties
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self._symbol.list_arguments()]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n)
                for n in self._symbol.list_arguments()]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n]
                for n in self._symbol.list_auxiliary_states()]

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    # -------------------------------------------------------------- compile
    def _signature(self, feed):
        return tuple((k, v.shape, str(v.dtype))
                     for k, v in sorted(feed.items()))

    def _get_fwd(self, feed, train):
        key = (self._signature(feed), train)
        fn = self._fwd_cache.get(key)
        if fn is None:
            self.num_compiles += 1
            sym = self._symbol
            from . import random as mxrand

            @jax.jit
            def fn(f, rng):
                # traced rng key: Dropout et al. stay stochastic per call
                with mxrand.trace_key_scope(rng):
                    aux_up = {}
                    outs = sym._interpret(
                        f, train=train,
                        aux_updates=aux_up if train else None)
                return outs, aux_up

            self._fwd_cache[key] = fn
        return fn

    def _get_bwd(self, diff_feed, const_feed, n_ograds):
        key = (self._signature(diff_feed), self._signature(const_feed))
        fn = self._bwd_cache.get(key)
        if fn is None:
            self.num_compiles += 1
            sym = self._symbol
            from . import random as mxrand

            @jax.jit
            def fn(diff, const, ograds, rng):
                def run(d):
                    merged = dict(d)
                    merged.update(const)
                    # same rng as the forward pass: identical dropout masks
                    with mxrand.trace_key_scope(rng):
                        return tuple(sym._interpret(merged, train=True))

                _, vjp = jax.vjp(run, diff)
                return vjp(tuple(ograds))[0]

            self._bwd_cache[key] = fn
        return fn

    # -------------------------------------------------------------- forward
    def forward(self, is_train=False, **kwargs):
        """Run the graph; returns ``self.outputs``.

        kwargs overwrite bound argument arrays by name (the reference copies
        into the bound NDArrays; here we rebind the device buffer, which is
        the same observable behavior without the copy).
        """
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"forward: unknown argument {k!r}")
            if not isinstance(v, NDArray):
                v = nd.array(v)
            dst = self.arg_dict[k]
            if tuple(v.shape) != tuple(dst.shape):
                raise MXNetError(
                    f"forward: shape mismatch for {k!r}: got {v.shape}, "
                    f"bound {dst.shape} (use Executor.reshape / a "
                    f"BucketingModule for new shapes)")
            data = v._data
            if data.dtype != dst._data.dtype:
                data = data.astype(dst._data.dtype)
            dst._set_data(data)
        feed = {n: a._data for n, a in self.arg_dict.items()}
        feed.update({n: a._data for n, a in self.aux_dict.items()})
        self._last_feed = feed
        self._is_train = bool(is_train)
        from . import random as mxrand
        from . import profiler as _prof
        self._last_rng = mxrand.next_key()
        with _prof.scope("Executor::forward", "symbolic"):
            outs, aux_up = self._get_fwd(feed, self._is_train)(
                feed, self._last_rng)
        for name, val in aux_up.items():
            if name in self.aux_dict:
                self.aux_dict[name]._set_data(val)
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        return self.outputs

    # ------------------------------------------------------------- backward
    def backward(self, out_grads=None):
        """Gradient of outputs wrt grad-requested args, honoring grad_req.

        With ``out_grads=None`` the cotangent is ones for every output —
        matching the reference head-gradient default for loss-style graphs
        (SoftmaxOutput/make_loss ignore the incoming cotangent anyway).
        """
        if self._last_feed is None:
            raise MXNetError("backward called before forward")
        if not self._is_train:
            raise MXNetError("backward requires forward(is_train=True)")
        diff_names = [n for n, r in self._grad_req.items() if r != "null"]
        if not diff_names:
            return
        diff = {n: self._last_feed[n] for n in diff_names}
        const = {n: v for n, v in self._last_feed.items()
                 if n not in diff}
        if out_grads is None:
            ograds = [jnp.ones_like(o._data) for o in self.outputs]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            ograds = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                      for g in out_grads]
        from . import profiler as _prof
        with _prof.scope("Executor::backward", "symbolic"):
            grads = self._get_bwd(diff, const, len(ograds))(
                diff, const, ograds, self._last_rng)
        for n in diff_names:
            dst = self.grad_dict[n]
            g = grads[n].astype(dst._data.dtype)
            if self._grad_req[n] == "add":
                dst._set_data(dst._data + g)
            else:
                dst._set_data(g)

    # ------------------------------------------------------------- utility
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """reference: Executor.copy_params_from."""
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                if tuple(arr.shape) != tuple(self.arg_dict[name].shape):
                    raise MXNetError(
                        f"copy_params_from: shape mismatch for {name!r}: "
                        f"{arr.shape} vs bound {self.arg_dict[name].shape}")
                self.arg_dict[name]._set_data(jnp.asarray(arr._data))
            elif not allow_extra_params:
                raise MXNetError(f"unknown parameter {name!r}")
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._set_data(jnp.asarray(arr._data))
                elif not allow_extra_params:
                    raise MXNetError(f"unknown aux state {name!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        """Return a new executor bound with new shapes (reference:
        Executor.reshape).  Compile caches are fresh; arrays are re-allocated
        for changed shapes and shared otherwise."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        args = {}
        for n, s in zip(self._symbol.list_arguments(), arg_shapes):
            cur = self.arg_dict[n]
            args[n] = cur if tuple(cur.shape) == tuple(s) else \
                nd.zeros(s, dtype=cur.dtype)
        aux = {}
        for n, s in zip(self._symbol.list_auxiliary_states(), aux_shapes):
            cur = self.aux_dict[n]
            aux[n] = cur if tuple(cur.shape) == tuple(s) else \
                nd.zeros(s, dtype=cur.dtype)
        grads = None
        if self.grad_dict:
            grads = {n: (g if tuple(g.shape) == tuple(args[n].shape)
                         else nd.zeros_like(args[n]))
                     for n, g in self.grad_dict.items()}
        return Executor(self._symbol, self._ctx, args, grads,
                        self._grad_req, aux)


def _as_dict(container, names, what) -> Dict[str, NDArray]:
    if isinstance(container, dict):
        return dict(container)
    if isinstance(container, (list, tuple)):
        if len(container) != len(names):
            raise MXNetError(
                f"{what}: expected {len(names)} arrays ({names}), "
                f"got {len(container)}")
        return dict(zip(names, container))
    raise MXNetError(f"{what} must be a dict or list of NDArray")
