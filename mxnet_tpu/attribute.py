"""Symbol attribute scoping.

Reference surface: ``python/mxnet/attribute.py`` — ``mx.AttrScope``
attaches string attributes (notably ``ctx_group`` for the manual model
parallelism of §2.4 P7 and ``__layout__`` hints) to every symbol created
inside the scope; ``Bind(group2ctx=...)`` then places subgraphs.

TPU-native: device placement of subgraphs is superseded by GSPMD
sharding — one logical memory space, XLA decides placement from sharding
annotations.  The scope machinery is kept at full fidelity (attributes
flow into the graph, serialize through Symbol JSON, and are queryable),
and ``ctx_group``/``group2ctx`` are accepted everywhere the reference
accepts them so model-parallel example code runs unchanged; the groups
act as sharding hints rather than hard device pins.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["AttrScope", "current_attrs"]


class _ScopeState(threading.local):
    def __init__(self):
        self.stack = []


_STATE = _ScopeState()


class AttrScope:
    """``with mx.AttrScope(ctx_group='dev1'):`` — every symbol created in
    the scope carries the attributes (reference: mx.AttrScope)."""

    def __init__(self, **attrs):
        self._attrs = {k: str(v) for k, v in attrs.items()}

    def __enter__(self):
        merged = dict(_STATE.stack[-1]) if _STATE.stack else {}
        merged.update(self._attrs)
        _STATE.stack.append(merged)
        return self

    def __exit__(self, *exc):
        _STATE.stack.pop()
        return False

    @classmethod
    def get(cls, attrs: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        """Merge current scope attrs with explicit ones (explicit win)."""
        out = dict(_STATE.stack[-1]) if _STATE.stack else {}
        if attrs:
            out.update({k: str(v) for k, v in attrs.items()})
        return out


def current_attrs() -> Dict[str, str]:
    return dict(_STATE.stack[-1]) if _STATE.stack else {}
