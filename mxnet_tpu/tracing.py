"""End-to-end request tracing + flight recorder (docs/observability.md).

The runtime-metrics registry answers *how much* and *how slow in
aggregate*; it cannot answer *where one slow request lost its time*.
The serving tier is three async layers deep (``ModelServer`` queues ->
``DynamicBatcher`` coalescing -> ``DecodeEngine`` token steps), so a
p99 in ``serving.request.seconds`` says nothing about whether the tail
came from queue wait, a bucket compile, prefill, or a starved decode
slot.  Production TPU serving is debugged span-by-span (the Gemma-on-
Cloud-TPU serving comparison attributes TTFT regressions to per-phase
timelines; tf.data's per-stage timing is the same idea on the input
path — PAPERS.md).  This module is that plane:

- **Spans**: named monotonic-clock intervals carrying a
  ``trace_id``/``span_id``/``parent_id`` triple and free-form tags.
  Every request gets ONE trace identity that survives all thread hops —
  contexts are handed across the batcher worker pool and the decode
  step loop explicitly (a span may be *started* in the caller's thread
  and *ended* in a worker).  Device calls that serve many traces at
  once (the shared batch execute, the fixed-shape decode step, a
  speculative ``decode.verify`` round) are recorded per interested
  trace via :func:`record_span` with the SAME interval — each trace
  keeps a complete private timeline (docs/observability.md lists the
  span taxonomy, including the §9 ``decode.prefill`` prefix-hit tags
  and ``decode.verify`` proposed/accepted tags).
- **Head-based sampling**: the keep/drop decision is made once, when
  the root span starts (``MXNET_TRACE_SAMPLE``, deterministic stride so
  tests are exact).  An unsampled request carries no context and every
  downstream span call is the no-op path.
- **Flight recorder**: completed traces land in a bounded ring
  (``MXNET_TRACE_RING``) — always the *most recent* N requests, which
  is what you want when a replica starts shedding: the ring plus
  ``ModelServer.debug_state()`` is dumped automatically on overload
  incidents (:func:`record_incident`) and on demand
  (``tools/diagnose.py``).
- **Exporters**: chrome-trace (``chrome://tracing`` / Perfetto) and
  JSON-lines.  ``runtime_metrics.Histogram`` exemplars link the two
  planes: ``observe(..., exemplar=trace_id)`` lets a Prometheus p99
  resolve to the exact trace that caused it.

The TRAINING plane rides the same tracer: ``perf_account`` roots one
``train.step`` trace per attributed ``ShardedTrainer`` step,
decomposed into ``train.data.wait`` / ``train.h2d`` /
``train.compute`` / ``train.collective`` / ``train.optimizer`` spans
(docs/observability.md span taxonomy), so a training timeline opens in
Perfetto next to a serving one and a slow ``trainer.step.seconds`` p99
resolves to its step trace through the same exemplar link.

Overhead contract (mirrors ``runtime_metrics``): tracing is **off by
default**; every instrumentation site either guards on the module-level
``_ENABLED`` bool or goes through :func:`span`/:func:`trace`, which
return a shared no-op singleton when the switch is off — one attribute
load + branch (~ns) per site.  Enable with ``MXNET_TRACE=1`` or
:func:`enable`.  Tracing never touches jax: with the switch in either
position, zero additional XLA programs are compiled.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import tempfile
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from . import engine
from .base import MXNetError, env_truthy, get_env

__all__ = [
    "Span", "TraceContext", "Tracer", "TRACER",
    "enable", "disable", "enabled", "reset",
    "trace", "span", "record_span", "tag",
    "current_span", "current_context",
    "to_chrome_trace", "dump_chrome_trace", "dump_jsonl",
    "flight_record", "record_incident", "incident_paths",
]

_LOG = logging.getLogger("mxnet_tpu")

# fast-path switch read by every instrumentation site (module attribute
# load + branch — the whole disabled-path cost)
_ENABLED = env_truthy("MXNET_TRACE", False)

# traces hold at most this many spans; a decode loop recording every
# step of a pathological sequence must degrade (drop + count), not grow
_MAX_SPANS_PER_TRACE = 2048
# active (incomplete) traces are bounded too: a request path that never
# closes its root (caller crashed between spans) must not leak forever
_MAX_ACTIVE_TRACES = 256

# one process-unique run prefix so trace ids from two replicas never
# collide in a merged dashboard
_RUN_PREFIX = os.urandom(4).hex()
_NEXT_ID = itertools.count(1)           # CPython: next() is atomic


def enable(sample=None):
    """Turn tracing on for this process (same as ``MXNET_TRACE=1``);
    optionally override the head-sampling rate (``sample=1.0`` traces
    everything)."""
    global _ENABLED
    _ENABLED = True
    if sample is not None:
        TRACER.set_sample(sample)


def disable():
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


class TraceContext:
    """The cross-thread handoff token: enough identity to parent a span
    started in another thread.  Existence implies *sampled* — an
    unsampled request's context is plain ``None`` everywhere, which
    keeps every downstream guard a single ``is None`` check."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"TraceContext({self.trace_id}/{self.span_id})"

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)

    def __hash__(self):
        return hash((self.trace_id, self.span_id))


class _NoopSpan:
    """Shared do-nothing span: what every tracing entry point returns
    when the switch is off or the request was not sampled.  One global
    instance; every method is a constant-time no-op."""

    __slots__ = ()
    sampled = False
    context = None
    tags = None
    t0 = t1 = 0.0

    def set_tag(self, key, value):
        return self

    def end(self, **tags):
        return None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __repr__(self):
        return "<noop span>"


_NOOP = _NoopSpan()

_TLS = threading.local()


def _tls_stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def current_span():
    """The innermost span entered (``with``) on THIS thread, or None.
    Cross-thread handoffs never use this — they pass a
    :class:`TraceContext` explicitly."""
    if not _ENABLED:
        return None
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


def current_context() -> Optional[TraceContext]:
    s = current_span()
    return s.context if s is not None else None


class Span:
    """One named interval of one trace.

    Starts at construction (``time.perf_counter``), ends at
    :meth:`end` (idempotent — first end wins, which makes the
    timeout-vs-worker race on queue-wait spans benign).  May be used as
    a context manager, which additionally installs it as the
    thread-local parent for :func:`span` calls made underneath it.
    Tag mutation is single-writer by convention (the thread currently
    driving the span); the tracer only reads tags after ``end``.
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "t1",
                 "tags", "thread", "_tracer", "_root")

    sampled = True

    def __init__(self, tracer, name, trace_id, parent_id, tags=None,
                 root=False):
        self.name = name
        self.trace_id = trace_id
        self.span_id = f"{next(_NEXT_ID):08x}"
        self.parent_id = parent_id
        self.t0 = time.perf_counter()
        self.t1 = None
        self.tags = dict(tags) if tags else None
        self.thread = threading.current_thread().name
        self._tracer = tracer
        self._root = root

    @property
    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def set_tag(self, key, value):
        if self.tags is None:
            self.tags = {}
        self.tags[key] = value
        return self

    def end(self, **tags):
        """Close the span (idempotent) and hand it to the tracer.  A
        root span's end completes its trace."""
        if self.t1 is not None:
            return
        self.t1 = time.perf_counter()
        for k, v in tags.items():
            self.set_tag(k, v)
        self._tracer._finish(self)

    def __enter__(self):
        _tls_stack().append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        st = _tls_stack()
        if st and st[-1] is self:
            st.pop()
        elif self in st:                # defensive: unbalanced nesting
            st.remove(self)
        if exc_type is not None:
            self.set_tag("error", exc_type.__name__)
        self.end()
        return False

    def to_dict(self) -> dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "t0": self.t0, "t1": self.t1, "thread": self.thread,
                "tags": dict(self.tags) if self.tags else {}}

    def __repr__(self):
        state = "open" if self.t1 is None else f"{self.t1 - self.t0:.6f}s"
        return (f"Span({self.name}, {self.trace_id}/{self.span_id}, "
                f"{state})")


class Tracer:
    """Span sink: sampling decisions, per-trace span buffers, and the
    bounded completed-trace ring (the flight recorder's storage).

    Span *starts* never take the lock — only :meth:`_finish` (append)
    and trace completion do, so the traced hot path pays one short
    uncontended lock hold per finished span.
    """

    def __init__(self, ring=None, sample=None):
        self._lock = engine.make_lock("tracing.Tracer._lock")
        if ring is None:
            ring = get_env("MXNET_TRACE_RING", typ=int)
        self.ring = max(1, int(ring))
        if sample is None:
            sample = get_env("MXNET_TRACE_SAMPLE", typ=float)
        self._sample = float(sample)
        self._heads = itertools.count()
        # trace_id -> {"root", "wall_time", "spans": [dict], "dropped"}
        self._active: "OrderedDict[str, dict]" = OrderedDict()
        self._completed = deque(maxlen=self.ring)
        self._stats = {"traces_started": 0, "traces_completed": 0,
                       "traces_evicted": 0, "traces_unsampled": 0,
                       "traces_aborted": 0, "spans": 0,
                       "spans_dropped": 0}

    # ------------------------------------------------------------ sampling
    @property
    def sample(self) -> float:
        return self._sample

    def set_sample(self, rate):
        rate = float(rate)
        if not 0.0 <= rate <= 1.0:
            raise MXNetError(
                f"trace sample rate must be in [0, 1], got {rate}")
        with self._lock:
            self._sample = rate

    def _sampled(self) -> bool:
        """Deterministic stride sampling: keep exactly
        ``floor((n+1)*rate) - floor(n*rate)`` of every head — rate 0.25
        keeps every 4th root, with no RNG state to perturb tests."""
        rate = self._sample
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        n = next(self._heads)
        return int((n + 1) * rate) > int(n * rate)

    # ------------------------------------------------------------- spans
    def start_trace(self, name, tags=None):
        """Root a new trace (the head-based sampling point).  Returns
        the root :class:`Span`, or the no-op span when sampled out."""
        if not self._sampled():
            with self._lock:
                self._stats["traces_unsampled"] += 1
            return _NOOP
        trace_id = f"{_RUN_PREFIX}{next(_NEXT_ID):010x}"
        sp = Span(self, name, trace_id, None, tags, root=True)
        with self._lock:
            self._stats["traces_started"] += 1
            self._active[trace_id] = {
                "root": sp.span_id, "wall_time": time.time(),
                "spans": [], "dropped": 0}
            # bound the incomplete set: a caller that dies between
            # spans must not leak its buffer forever
            while len(self._active) > _MAX_ACTIVE_TRACES:
                self._active.popitem(last=False)
                self._stats["traces_aborted"] += 1
        return sp

    def start_span(self, name, parent=None, tags=None):
        """Child span under ``parent`` (a :class:`TraceContext`, a
        :class:`Span`, or None for the current thread-local span).
        Never roots a trace: with no resolvable parent the call is the
        no-op path — traces start only at :meth:`start_trace`."""
        if parent is None:
            parent = current_context()
        elif isinstance(parent, (Span, _NoopSpan)):
            parent = parent.context
        if parent is None:
            return _NOOP
        return Span(self, name, parent.trace_id, parent.span_id, tags)

    def record_span(self, name, parent, t0, t1, tags=None):
        """Append an already-timed span (the decode step loop times one
        device call and attributes it to several sequences)."""
        if parent is None:
            return None
        if isinstance(parent, (Span, _NoopSpan)):
            parent = parent.context
            if parent is None:
                return None
        sp = Span(self, name, parent.trace_id, parent.span_id, tags)
        sp.t0 = t0
        sp.t1 = t1
        self._finish(sp)
        return sp

    def _finish(self, sp: Span):
        done = None
        with self._lock:
            buf = self._active.get(sp.trace_id)
            if buf is None:
                # trace already completed (or aborted): a straggler
                # ending after the root is dropped, not resurrected
                self._stats["spans_dropped"] += 1
                return
            if len(buf["spans"]) >= _MAX_SPANS_PER_TRACE:
                buf["dropped"] += 1
                self._stats["spans_dropped"] += 1
            else:
                buf["spans"].append(sp.to_dict())
                self._stats["spans"] += 1
            if sp.span_id == buf["root"]:
                del self._active[sp.trace_id]
                done = {"trace_id": sp.trace_id, "root": sp.name,
                        "wall_time": buf["wall_time"],
                        "duration": (sp.t1 or sp.t0) - sp.t0,
                        "dropped_spans": buf["dropped"],
                        "spans": sorted(buf["spans"],
                                        key=lambda s: s["t0"])}
                if len(self._completed) == self._completed.maxlen:
                    self._stats["traces_evicted"] += 1
                self._completed.append(done)
                self._stats["traces_completed"] += 1

    # ------------------------------------------------------------ readers
    def traces(self, n=None) -> List[dict]:
        """Completed traces, oldest first (the flight-recorder ring)."""
        with self._lock:
            out = list(self._completed)
        return out if n is None else out[-n:]

    def find(self, trace_id) -> Optional[dict]:
        with self._lock:
            for tr in self._completed:
                if tr["trace_id"] == trace_id:
                    return tr
        return None

    def last(self, root=None) -> Optional[dict]:
        """Most recent completed trace (optionally: whose root span has
        name ``root``)."""
        with self._lock:
            for tr in reversed(self._completed):
                if root is None or tr["root"] == root:
                    return tr
        return None

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["active"] = len(self._active)
            out["completed"] = len(self._completed)
        out["enabled"] = _ENABLED
        out["sample"] = self._sample
        out["ring"] = self.ring
        return out

    def reset(self):
        """Drop every buffered trace and zero the counters (tests)."""
        with self._lock:
            self._active.clear()
            self._completed.clear()
            for k in self._stats:
                self._stats[k] = 0


TRACER = Tracer()


def reset():
    TRACER.reset()


# ---------------------------------------------------------------------------
# Module-level instrumentation helpers (the hot-path entry points)
# ---------------------------------------------------------------------------

def trace(name, **tags):
    """Root a new trace; returns the root span (or the no-op span when
    tracing is off / sampled out).  Use as a context manager around one
    request."""
    if not _ENABLED:
        return _NOOP
    return TRACER.start_trace(name, tags or None)


def span(name, parent=None, **tags):
    """Child span under ``parent`` (explicit cross-thread context, or
    the current thread-local span).  No parent resolvable -> no-op."""
    if not _ENABLED:
        return _NOOP
    return TRACER.start_span(name, parent=parent, tags=tags or None)


def record_span(name, parent, t0, t1, tags=None):
    """Append a span with explicit timestamps (no-op when off or when
    ``parent`` is None)."""
    if not _ENABLED:
        return None
    return TRACER.record_span(name, parent, t0, t1, tags)


def tag(key, value):
    """Tag the current thread-local span, if any (the batcher annotates
    whatever span the worker entered, without threading handles)."""
    if not _ENABLED:
        return
    s = current_span()
    if s is not None:
        s.set_tag(key, value)


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------

def to_chrome_trace(traces) -> dict:
    """Render completed trace dicts as a chrome-trace JSON object
    (``chrome://tracing`` / Perfetto: ``ph:"X"`` complete events, ts in
    microseconds, one row per span thread).  Accepts one trace dict or
    a list of them."""
    if isinstance(traces, dict):
        traces = [traces]
    pid = os.getpid()
    events = [{"name": "process_name", "ph": "M", "pid": pid,
               "args": {"name": "mxnet_tpu"}}]
    for tr in traces:
        for s in tr["spans"]:
            dur = max(0.0, (s["t1"] or s["t0"]) - s["t0"])
            args = dict(s["tags"])
            args.update({"trace_id": s["trace_id"],
                         "span_id": s["span_id"],
                         "parent_id": s["parent_id"]})
            events.append({"name": s["name"], "cat": tr["root"],
                           "ph": "X", "ts": s["t0"] * 1e6,
                           "dur": dur * 1e6, "pid": pid,
                           "tid": s["thread"], "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump_chrome_trace(path, traces=None) -> str:
    """Write chrome-trace JSON for ``traces`` (default: the whole
    completed ring) to ``path``; returns the path."""
    if traces is None:
        traces = TRACER.traces()
    with open(path, "w") as f:
        json.dump(to_chrome_trace(traces), f)
    return path


def dump_jsonl(path=None, traces=None) -> str:
    """One JSON object per span, one span per line (log-pipeline
    friendly).  Returns the serialized text; also writes it when
    ``path`` is given."""
    if traces is None:
        traces = TRACER.traces()
    elif isinstance(traces, dict):
        traces = [traces]
    lines = []
    for tr in traces:
        for s in tr["spans"]:
            rec = dict(s)
            rec["root"] = tr["root"]
            lines.append(json.dumps(rec, sort_keys=True))
    text = "\n".join(lines) + ("\n" if lines else "")
    if path is not None:
        with open(path, "w") as f:
            f.write(text)
    return text


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------

# incident bookkeeping lives under its own lock: record_incident is
# called from shed paths that may already hold serving locks released —
# the tracer lock is never needed here beyond the reader calls
_INCIDENT_LOCK = engine.make_lock("tracing._INCIDENT_LOCK")
_INCIDENTS: Dict[str, object] = {"last": 0.0, "count": 0,
                                 "paths": deque(maxlen=16)}
_INCIDENT_MIN_INTERVAL = 30.0


def flight_record(state=None) -> dict:
    """The flight-recorder snapshot: tracer stats + the completed-trace
    ring, plus whatever server ``state`` the caller attaches
    (``ModelServer.debug_state()``).  Under an active chaos plan
    (``MXNET_FAULTS``) the record also carries the plan spec and its
    fired-fault counters — an incident dump from a chaos run must say
    which injected faults the stack was absorbing at the time."""
    record = {"wall_time": time.time(),
              "tracer": TRACER.stats(),
              "traces": TRACER.traces(),
              "state": state}
    from . import faults as _faults        # lazy: faults imports tracing
    plan = _faults.active()
    if plan is not None:
        record["faults"] = {"spec": plan.spec,
                            "fired": plan.counters()}
    return record


def record_incident(reason, state=None, path=None, min_interval=None):
    """Dump the flight recorder to disk because something went wrong
    (load shedding, an eviction storm, a decode step failure).

    ``state`` may be a dict or a zero-arg callable (evaluated only when
    the dump actually happens — debounce keeps a shedding storm from
    serializing the server state per rejected request).  Dumps are
    rate-limited to one per ``min_interval`` seconds (default 30);
    returns the written path, or None when debounced/disabled.
    """
    if not _ENABLED:
        return None
    interval = _INCIDENT_MIN_INTERVAL if min_interval is None \
        else float(min_interval)
    now = time.monotonic()
    with _INCIDENT_LOCK:
        if now - _INCIDENTS["last"] < interval and _INCIDENTS["count"]:
            return None
        _INCIDENTS["last"] = now
        _INCIDENTS["count"] += 1
        seq = _INCIDENTS["count"]
    if callable(state):
        try:
            state = state()
        except Exception as e:          # noqa: BLE001 — best effort
            state = {"error": f"debug_state failed: {e}"}
    record = flight_record(state)
    record["reason"] = reason
    if path is None:
        path = os.path.join(
            tempfile.gettempdir(),
            f"mxnet_flight_{os.getpid()}_{seq:03d}.json")
    try:
        with open(path, "w") as f:
            json.dump(record, f, default=str)
    except OSError as e:
        _LOG.warning("tracing: flight-recorder dump failed: %s", e)
        return None
    with _INCIDENT_LOCK:
        _INCIDENTS["paths"].append(path)
    _LOG.warning("tracing: incident %r — flight record dumped to %s "
                 "(%d trace(s))", reason, path, len(record["traces"]))
    return path


def incident_paths() -> List[str]:
    """Paths of the flight-recorder dumps written so far."""
    with _INCIDENT_LOCK:
        return list(_INCIDENTS["paths"])
