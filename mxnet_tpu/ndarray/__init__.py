"""``mx.nd`` namespace: NDArray + generated op functions + creation API.

Reference: ``python/mxnet/ndarray/`` — at import, op functions are
*generated* from the registry (the MXListAllOpNames / _make_ndarray_function
codegen pattern, SURVEY.md 2.2).
"""
from __future__ import annotations

import sys
import types

import numpy as _np
import jax.numpy as _jnp

from ..base import MXNetError
from ..context import Context, current_context
from ..engine import waitall
from .ndarray import NDArray
from ..ops import registry as _reg

# ---------------------------------------------------------------------------
# Generated op namespace (mx.nd.op.* and re-exported as mx.nd.*)
# ---------------------------------------------------------------------------

op = types.ModuleType(__name__ + ".op")
op.__doc__ = "Auto-generated operator functions (one per registered op)."
for _name in _reg.list_ops():
    setattr(op, _name, _reg.make_frontend(_reg.get_op(_name)))
sys.modules[op.__name__] = op

_EXCLUDE = {"sum", "max", "min", "abs", "round"}  # need wrapper care below


def _reexport():
    g = globals()
    for _name in _reg.list_ops():
        if _name not in g:
            g[_name] = getattr(op, _name)


def invoke_by_name(name, inputs, kwargs, out=None):
    return _reg.invoke(_reg.get_op(name), inputs, kwargs, out=out)


# ---------------------------------------------------------------------------
# Creation API (reference: python/mxnet/ndarray/utils.py + ndarray.py)
# ---------------------------------------------------------------------------

def array(source_array, ctx: Context = None, dtype=None) -> NDArray:
    if isinstance(source_array, NDArray):
        src = source_array._data
    elif isinstance(source_array, _np.ndarray):
        src = source_array  # keep explicit numpy dtype (reference behavior)
    else:
        src = _np.asarray(source_array)
        if dtype is None and src.dtype in (_np.float64, _np.int64,
                                           _np.int32):
            dtype = "float32"  # reference: python lists default to float32
    return NDArray(src, ctx=ctx, dtype=dtype)


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx=None, dtype="float32", **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_jnp.zeros(shape, dtype=_jnp.dtype(dtype)), ctx=ctx)


def ones(shape, ctx=None, dtype="float32", **kwargs):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_jnp.ones(shape, dtype=_jnp.dtype(dtype)), ctx=ctx)


def full(shape, val, ctx=None, dtype="float32", out=None):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_jnp.full(shape, val, dtype=_jnp.dtype(dtype)), ctx=ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    out = _jnp.arange(start, stop, step, dtype=_jnp.dtype(dtype))
    if repeat > 1:
        out = _jnp.repeat(out, repeat)
    return NDArray(out, ctx=ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype="float32"):
    return NDArray(_jnp.linspace(start, stop, num, endpoint=endpoint,
                                 dtype=_jnp.dtype(dtype)), ctx=ctx)


def eye(N, M=0, k=0, ctx=None, dtype="float32"):
    return NDArray(_jnp.eye(N, M if M else None, k,
                            dtype=_jnp.dtype(dtype)), ctx=ctx)


def zeros_like(arr, **kw):
    return NDArray(_jnp.zeros_like(arr._data))


def ones_like(arr, **kw):
    return NDArray(_jnp.ones_like(arr._data))


def moveaxis(arr, source, destination):
    return NDArray(_jnp.moveaxis(arr._data, source, destination))


def concatenate(arrays, axis=0, always_copy=True):
    return op.concat(*arrays, dim=axis)


def stack_arrays(arrays, axis=0):
    return op.stack(*arrays, axis=axis)


def add_n(*arrays):
    out = arrays[0]
    for a in arrays[1:]:
        out = out + a
    return out


ElementWiseSum = add_n


# ---------------------------------------------------------------------------
# Serialization (reference: MXNDArraySave/Load — the .params file format).
# Container format here is NPZ (portable, inspectable); the save/load API
# contract (dict-of-name->array or list) matches the reference.
# ---------------------------------------------------------------------------

def save(fname, data):
    if isinstance(data, NDArray):
        data = [data]
    # pass a file object so numpy does not append ".npz" to the name
    if isinstance(data, dict):
        arrays = {k: v.asnumpy() for k, v in data.items()}
        with open(fname, "wb") as f:
            _np.savez(f, __mx_format__="dict", **arrays)
    elif isinstance(data, (list, tuple)):
        arrays = {f"__arr_{i}": v.asnumpy() for i, v in enumerate(data)}
        with open(fname, "wb") as f:
            _np.savez(f, __mx_format__="list", **arrays)
    else:
        raise MXNetError("save: data must be NDArray, list or dict")


def load(fname):
    from ..compat import is_dmlc_params, load_params_dmlc
    if is_dmlc_params(fname):
        # legacy upstream .params container (migration shim)
        return load_params_dmlc(fname)
    with _np.load(fname, allow_pickle=False) as z:
        fmt = str(z["__mx_format__"]) if "__mx_format__" in z else "dict"
        if fmt == "list":
            n = len([k for k in z.files if k.startswith("__arr_")])
            return [array(z[f"__arr_{i}"]) for i in range(n)]
        return {k: array(z[k]) for k in z.files if k != "__mx_format__"}


def from_dlpack(ext):
    """Wrap an external DLPack tensor/capsule as an NDArray (reference:
    mx.nd.from_dlpack).  Zero-copy for host buffers; accepts any object
    with ``__dlpack__`` (torch/numpy tensors) or a raw capsule."""
    import jax.numpy as jnp
    return NDArray(jnp.from_dlpack(ext))


def from_numpy(arr, zero_copy=True):
    """Reference: mx.nd.from_numpy — host-array import (the backing
    buffer is copied to the device; zero_copy is best-effort)."""
    return array(arr)


# random namespace: mx.nd.random.uniform etc.
from .. import random as random  # noqa: E402

# sparse namespace: mx.nd.sparse.csr_matrix etc.
from . import sparse  # noqa: E402
from .sparse import CSRNDArray, RowSparseNDArray  # noqa: E402

_reexport()

# NumPy-ish aliases the reference exposes at nd level
waitall = waitall  # re-export
