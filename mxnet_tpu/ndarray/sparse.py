"""Sparse NDArray: CSR and RowSparse storage types.

Reference: ``python/mxnet/ndarray/sparse.py`` over ``kCSRStorage`` /
``kRowSparseStorage`` (``include/mxnet/ndarray.h`` — SURVEY.md 2.1 NDArray
row).  The reference uses sparse for (a) sparse input matrices (CSR dot)
and (b) sparse gradients (row_sparse Embedding grads + lazy optimizer row
updates).

TPU-native redesign: XLA has no native sparse tensors — the MXU wants
dense tiles — so sparse here is a *layout over dense device buffers*
(data/indices[/indptr] jax arrays) whose ops compile to XLA gather /
scatter-add / segment-sum, which is exactly how sparse workloads map to
TPU efficiently.  Any op without a sparse implementation transparently
falls back to the dense path by materializing (the reference's "storage
fallback" mechanism, src/executor/infer_graph_attr_pass.cc) — correctness
first, with the dense cost visible in the profiler rather than a crash.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .ndarray import NDArray, _resolve_dtype

__all__ = ["BaseSparseNDArray", "CSRNDArray", "RowSparseNDArray",
           "csr_matrix", "row_sparse_array", "zeros", "empty", "array",
           "retain", "dot", "add", "elemwise_add", "tostype"]


class BaseSparseNDArray(NDArray):
    """Common machinery: components + lazy dense materialization."""

    __slots__ = ("_sparse_shape", "_sparse_dtype", "_dense_cache",
                 "_components")

    def __init__(self, components: dict, shape, dtype):
        # Deliberately NOT calling NDArray.__init__: there is no dense
        # buffer yet.  Engine/autograd fields are set up manually.
        from ..engine import Var, engine
        self._components = {k: (v._data if isinstance(v, NDArray)
                                else jnp.asarray(v))
                            for k, v in components.items()}
        self._sparse_shape = tuple(int(s) for s in shape)
        self._sparse_dtype = _resolve_dtype(dtype) or \
            self._components["data"].dtype
        self._dense_cache = None
        self._ctx = None
        self._var = Var()
        self._grad = None
        self._grad_req = "null"
        self._autograd_node = None
        self._lazy_cb = None
        engine().track(self)

    # -- the dense fallback hook -------------------------------------------
    @property
    def _data(self):
        """Dense materialization (storage fallback).  Dense-only ops read
        this transparently; the conversion is one XLA scatter."""
        if self._dense_cache is None:
            self._dense_cache = self._to_dense_jax()
        return self._dense_cache

    @_data.setter
    def _data(self, value):  # pragma: no cover - guard
        raise MXNetError(
            f"cannot assign a dense buffer into a {self.stype} array; "
            f"convert with tostype('default') first")

    def _set_data(self, new_data):
        raise MXNetError(
            f"in-place write on a {self.stype} array is not supported; "
            f"convert with tostype('default') first")

    @property
    def shape(self):
        return self._sparse_shape

    @property
    def dtype(self):
        return np.dtype(self._sparse_dtype) \
            if self._sparse_dtype != jnp.bfloat16 else self._sparse_dtype

    @property
    def size(self):
        n = 1
        for s in self._sparse_shape:
            n *= s
        return n

    @property
    def ndim(self):
        return len(self._sparse_shape)

    @property
    def data(self) -> NDArray:
        """The non-zero values array (reference: CSRNDArray.data)."""
        return NDArray(self._components["data"])

    @property
    def indices(self) -> NDArray:
        return NDArray(self._components["indices"])

    def asnumpy(self):
        return np.asarray(self._data)

    def todense(self) -> NDArray:
        return NDArray(self._to_dense_jax())

    def tostype(self, stype: str):
        if stype == self.stype:
            return self
        if stype == "default":
            return self.todense()
        return _from_dense(self.todense(), stype)

    def astype(self, dtype, copy=True):
        comp = dict(self._components)
        comp["data"] = comp["data"].astype(_resolve_dtype(dtype))
        return type(self)._from_components(comp, self._sparse_shape)

    def copy(self):
        return type(self)._from_components(dict(self._components),
                                           self._sparse_shape)

    def copyto(self, other):
        raise MXNetError("copyto on sparse arrays is not supported; "
                         "use tostype/todense")

    def wait_to_read(self):
        self._var.check()
        for v in self._components.values():
            jax.block_until_ready(v)

    def __repr__(self):
        return (f"<{type(self).__name__} {self.shape} "
                f"{self.dtype} nnz-storage={self._components['data'].shape}>")


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference: CSRNDArray)."""

    __slots__ = ()

    @property
    def stype(self):
        return "csr"

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._components["indptr"])

    @classmethod
    def _from_components(cls, comp, shape):
        return cls(comp, shape, comp["data"].dtype)

    def _to_dense_jax(self):
        data = self._components["data"]
        indices = self._components["indices"].astype(jnp.int32)
        indptr = self._components["indptr"].astype(jnp.int32)
        nnz = data.shape[0]
        rows, cols = self._sparse_shape
        # row id per stored element from indptr: one searchsorted, no loop
        row_ids = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
        out = jnp.zeros((rows, cols), data.dtype)
        return out.at[row_ids, indices].add(data)

    def __getitem__(self, key):
        if isinstance(key, int):
            nrows = self._sparse_shape[0]
            if key < 0:
                key += nrows
            if not 0 <= key < nrows:
                raise MXNetError(f"row index {key} out of range "
                                 f"for {self.shape}")
            key = slice(key, key + 1)
        if not isinstance(key, slice) or key.step not in (None, 1):
            raise MXNetError("CSR supports only contiguous row slicing")
        start, stop, _ = key.indices(self._sparse_shape[0])
        indptr = self._components["indptr"].astype(jnp.int32)
        s, e = int(indptr[start]), int(indptr[stop])
        comp = {"data": self._components["data"][s:e],
                "indices": self._components["indices"][s:e],
                "indptr": indptr[start:stop + 1] - s}
        return CSRNDArray(comp, (stop - start, self._sparse_shape[1]),
                          comp["data"].dtype)


class RowSparseNDArray(BaseSparseNDArray):
    """First-dim-sparse tensor: (indices, rows) (reference:
    RowSparseNDArray) — the gradient type for embedding-style lookups."""

    __slots__ = ()

    @property
    def stype(self):
        return "row_sparse"

    @classmethod
    def _from_components(cls, comp, shape):
        return cls(comp, shape, comp["data"].dtype)

    def _to_dense_jax(self):
        data = self._components["data"]
        indices = self._components["indices"].astype(jnp.int32)
        out = jnp.zeros(self._sparse_shape, data.dtype)
        return out.at[indices].add(data)

    def retain(self, indices):
        """Keep only the given rows (reference: sparse.retain)."""
        keep = jnp.asarray(indices, jnp.int32)
        mine = self._components["indices"].astype(jnp.int32)
        mask = jnp.isin(mine, keep)
        sel = np.flatnonzero(np.asarray(mask))
        comp = {"data": self._components["data"][sel],
                "indices": mine[sel]}
        return RowSparseNDArray(comp, self._sparse_shape,
                                comp["data"].dtype)


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def csr_matrix(arg1, shape=None, ctx=None, dtype=None) -> CSRNDArray:
    """csr_matrix((data, indices, indptr), shape=(M, N)) or from a dense
    array/NDArray (reference: mx.nd.sparse.csr_matrix)."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = jnp.asarray(data, _resolve_dtype(dtype))
        comp = {"data": data,
                "indices": jnp.asarray(indices, jnp.int32),
                "indptr": jnp.asarray(indptr, jnp.int32)}
        if shape is None:
            raise MXNetError("csr_matrix: shape required with components")
        return CSRNDArray(comp, shape, data.dtype)
    dense = arg1.asnumpy() if isinstance(arg1, NDArray) else np.asarray(arg1)
    if dense.ndim != 2:
        raise MXNetError("csr_matrix: dense input must be 2-D")
    mask = dense != 0
    indptr = np.concatenate([[0], mask.sum(axis=1).cumsum()])
    rows, cols = np.nonzero(mask)
    comp = {"data": jnp.asarray(dense[rows, cols], _resolve_dtype(dtype)),
            "indices": jnp.asarray(cols, jnp.int32),
            "indptr": jnp.asarray(indptr, jnp.int32)}
    return CSRNDArray(comp, dense.shape, comp["data"].dtype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None) \
        -> RowSparseNDArray:
    """row_sparse_array((data, indices), shape=...) or from dense
    (reference: mx.nd.sparse.row_sparse_array)."""
    if isinstance(arg1, tuple) and len(arg1) == 2 and not \
            isinstance(arg1[0], int):
        data, indices = arg1
        data = jnp.asarray(data, _resolve_dtype(dtype))
        if shape is None:
            raise MXNetError("row_sparse_array: shape required")
        return RowSparseNDArray({"data": data,
                                 "indices": jnp.asarray(indices,
                                                        jnp.int32)},
                                shape, data.dtype)
    if isinstance(arg1, NDArray):
        # device path: compute the row mask on device and transfer only
        # the boolean mask (O(rows) bits), then gather rows on device —
        # never the full dense tensor (Trainer calls this per step for
        # sparse_grad params)
        d = arg1._data
        mask = jnp.any(d.reshape(d.shape[0], -1) != 0, axis=1)
        nz_rows = np.flatnonzero(np.asarray(mask))
        comp = {"data": d[nz_rows].astype(_resolve_dtype(dtype)
                                          or d.dtype),
                "indices": jnp.asarray(nz_rows, jnp.int32)}
        return RowSparseNDArray(comp, d.shape, comp["data"].dtype)
    dense = np.asarray(arg1)
    nz_rows = np.flatnonzero(
        (dense.reshape(dense.shape[0], -1) != 0).any(axis=1))
    comp = {"data": jnp.asarray(dense[nz_rows], _resolve_dtype(dtype)),
            "indices": jnp.asarray(nz_rows, jnp.int32)}
    return RowSparseNDArray(comp, dense.shape, comp["data"].dtype)


def zeros(stype, shape, ctx=None, dtype="float32"):
    """reference: mx.nd.sparse.zeros."""
    dtype = _resolve_dtype(dtype)
    if stype == "csr":
        return CSRNDArray({"data": jnp.zeros((0,), dtype),
                           "indices": jnp.zeros((0,), jnp.int32),
                           "indptr": jnp.zeros((shape[0] + 1,), jnp.int32)},
                          shape, dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(
            {"data": jnp.zeros((0,) + tuple(shape[1:]), dtype),
             "indices": jnp.zeros((0,), jnp.int32)}, shape, dtype)
    if stype == "default":
        from . import zeros as dense_zeros
        return dense_zeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError(f"unknown stype {stype!r}")


empty = zeros


def array(source, ctx=None, dtype=None):
    """Sparse-preserving nd.sparse.array (reference)."""
    if isinstance(source, BaseSparseNDArray):
        return source.copy()
    raise MXNetError("sparse.array expects a sparse input; use "
                     "csr_matrix/row_sparse_array to construct")


def _from_dense(arr: NDArray, stype: str):
    if stype == "csr":
        return csr_matrix(arr)
    if stype == "row_sparse":
        return row_sparse_array(arr)
    raise MXNetError(f"unknown stype {stype!r}")


def tostype(arr, stype: str):
    """Free-function stype conversion covering dense arrays too."""
    if isinstance(arr, BaseSparseNDArray):
        return arr.tostype(stype)
    if stype == "default":
        return arr
    return _from_dense(arr, stype)


# ---------------------------------------------------------------------------
# sparse ops
# ---------------------------------------------------------------------------

def retain(data: RowSparseNDArray, indices):
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    idx = indices._data if isinstance(indices, NDArray) else indices
    return data.retain(idx)


def dot(lhs, rhs, transpose_a=False, transpose_b=False) -> NDArray:
    """dot(csr, dense) / dot(csr.T, dense) — the sparse kernel the
    reference ships for libsvm-style input pipelines
    (reference: src/operator/tensor/dot.cc sparse paths).
    Lowers to one XLA gather + segment-sum / scatter-add."""
    if not isinstance(lhs, CSRNDArray):
        from . import dot as dense_dot
        return dense_dot(lhs, rhs, transpose_a=transpose_a,
                         transpose_b=transpose_b)
    if transpose_b:
        raise MXNetError("dot(csr, dense, transpose_b=True) unsupported")
    data = lhs._components["data"]
    col = lhs._components["indices"].astype(jnp.int32)
    indptr = lhs._components["indptr"].astype(jnp.int32)
    nnz = data.shape[0]
    rows, cols = lhs.shape
    dense = rhs._data
    row_ids = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
    if not transpose_a:
        # out[r] = Σ_j a[r,j] * dense[j] : gather rows of dense by column
        # index, weight, segment-sum into output rows
        contrib = data[:, None] * dense[col]          # (nnz, k)
        out = jax.ops.segment_sum(contrib, row_ids, num_segments=rows)
    else:
        # out[c] = Σ_r a[r,c] * dense[r] : scatter-add by column index
        contrib = data[:, None] * dense[row_ids]
        out = jnp.zeros((cols, dense.shape[1]), data.dtype) \
            .at[col].add(contrib)
    return NDArray(out)


def add(lhs, rhs) -> NDArray:
    """sparse + sparse/dense → dense (fallback add, reference semantics
    keep rsp+rsp sparse; dense result is the safe superset here)."""
    return NDArray(lhs._data + rhs._data)


elemwise_add = add
