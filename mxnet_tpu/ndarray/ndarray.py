"""NDArray: the framework's value type, over jax.Array.

Reference: ``include/mxnet/ndarray.h`` + ``src/ndarray/ndarray.cc`` and the
Python class in ``python/mxnet/ndarray/ndarray.py`` (SURVEY.md 2.1, 3.1).

TPU-native redesign: a ``jax.Array`` IS already the lazy, asynchronous,
engine-scheduled buffer the reference hand-built (PJRT dispatch is async;
the array is a future).  What this class adds on top:

- the engine **Var** (version counter + deferred-exception slot) giving the
  reference's ``WaitToRead`` / async-error-propagation contract;
- autograd hooks (``attach_grad``, ``.grad``, ``backward`` — tape links);
- the reference API surface: context placement, ``asnumpy``, rich indexing,
  arithmetic dunders routed through the op registry (so autograd records
  them), shape-method sugar, and save/load.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..base import MXNetError, get_env
from ..context import Context, current_context
from .. import engine as _engine_mod
from ..engine import Var, engine

__all__ = ["NDArray"]

_DTYPE_ALIASES = {
    "float32": np.float32, "float64": np.float64, "float16": np.float16,
    "bfloat16": jnp.bfloat16, "int8": np.int8, "uint8": np.uint8,
    "int32": np.int32, "int64": np.int64, "bool": np.bool_,
}


def _resolve_dtype(dtype):
    if dtype is None:
        return None
    if isinstance(dtype, str):
        return jnp.dtype(_DTYPE_ALIASES.get(dtype, dtype))
    return jnp.dtype(dtype)


class NDArray:
    """Multi-dimensional array on a device (see module docstring)."""

    __slots__ = ("_data", "_ctx", "_var", "_grad", "_grad_req",
                 "_autograd_node", "_lazy_cb", "__weakref__")

    # NumPy interop precedence so ndarray + NDArray defers to us
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Context = None, dtype=None):
        dtype = _resolve_dtype(dtype)
        if isinstance(data, NDArray):
            data = data._data
        if not isinstance(data, jax.Array):
            data = jnp.asarray(data, dtype=dtype)
        elif dtype is not None and data.dtype != dtype:
            data = data.astype(dtype)
        if ctx is not None and not isinstance(data, jax.core.Tracer):
            dev = ctx.jax_device()
            if data.device != dev:
                data = jax.device_put(data, dev)
        self._data = data
        self._ctx = ctx
        self._var = Var()
        self._grad = None
        self._grad_req = "null"
        self._autograd_node = None
        self._lazy_cb = None
        engine().track(self)

    @classmethod
    def _deferred(cls, aval, materialize_cb, ctx=None):
        """A lazy NDArray: ``_data`` holds a jax.ShapeDtypeStruct (so
        shape/dtype/size/ndim work) until ``materialize_cb`` fills the
        real value — the engine-style async handle behind CachedOp's
        deferred forward (reference: every NDArray was such a future
        under the ThreadedEngine; reads blocked at WaitToRead)."""
        obj = cls.__new__(cls)
        obj._data = aval
        obj._ctx = ctx
        obj._var = Var()
        obj._grad = None
        obj._grad_req = "null"
        obj._autograd_node = None
        obj._lazy_cb = materialize_cb
        engine().track(obj)
        return obj

    def _lazy_materialize(self):
        cb, self._lazy_cb = self._lazy_cb, None
        if cb is not None:
            cb()        # fills _data (for every output of the program)

    # ------------------------------------------------------------------ data
    @property
    def data_jax(self):
        """The underlying jax.Array (TPU-build extension point)."""
        return self._data

    def _set_data(self, new_data):
        """In-place value replacement; bumps the engine var version
        (reference: write op on ThreadedVar)."""
        if _engine_mod._SANITIZE:
            engine()._sanitize_check_registered(self)
        self._data = new_data
        self._var.bump()

    def _in_grad_graph(self):
        return self._autograd_node is not None or (
            self._grad is not None and self._grad_req != "null")

    # ------------------------------------------------------------- properties
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype) if self._data.dtype != jnp.bfloat16 \
            else self._data.dtype

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self) -> Context:
        if self._ctx is not None:
            return self._ctx
        if isinstance(self._data, jax.core.Tracer):
            # inside a trace there is no physical placement yet
            return current_context()
        dev = self._data.device
        plat = getattr(dev, "platform", "cpu")
        # index into the LOCAL device list: under jax.distributed, global
        # device ids are offset per process (worker 1's first cpu device
        # is id 2048) while Context numbering is per-process
        if plat == "cpu":
            try:
                idx = jax.local_devices(backend="cpu").index(dev)
            except (ValueError, RuntimeError):
                idx = 0
            return Context("cpu", idx)
        accel = [d for d in jax.local_devices() if d.platform != "cpu"]
        try:
            idx = accel.index(dev)
        except ValueError:
            idx = 0
        return Context("tpu", idx)

    ctx = context

    @property
    def T(self):
        return self.transpose()

    @property
    def stype(self):
        return "default"

    @property
    def grad(self):
        if self._grad is not None:
            from .. import autograd
            if autograd._STATE.pending is not None:
                autograd.flush_pending()    # deferred backward: materialize
        return self._grad

    # --------------------------------------------------------------- engine
    def wait_to_read(self):
        """Block until computed; re-raise any deferred async error
        (reference: NDArray::WaitToRead + exception-on-var rethrow)."""
        if self._lazy_cb is not None:
            self._lazy_materialize()               # deferred forward
        from .. import autograd
        if autograd._STATE.pending is not None:
            autograd.flush_if_pending_grad(self)   # stale grad-alias read
        self._var.check()
        try:
            self._data.block_until_ready()
        except Exception as e:
            self._var.set_exception(e)
            raise
        return self

    wait_to_write = wait_to_read

    # -------------------------------------------------------------- convert
    def asnumpy(self) -> np.ndarray:
        self.wait_to_read()
        return np.asarray(self._data)

    # DLPack interop (reference: NDArray DLPack methods over
    # include/mxnet/tensor_blob.h DLTensor).  Zero-copy where the backing
    # PJRT buffer is host/GPU memory; arrays are immutable here, so the
    # "for_write" variant shares the read contract and mutation of the
    # exported view is undefined (the reference's write capsule mutates
    # in place — not expressible over immutable XLA buffers).
    def to_dlpack_for_read(self):
        self.wait_to_read()
        return self._data.__dlpack__()

    to_dlpack_for_write = to_dlpack_for_read

    def __dlpack__(self, *args, **kwargs):
        self.wait_to_read()
        return self._data.__dlpack__(*args, **kwargs)

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def tolist(self):
        return self.asnumpy().tolist()

    def astype(self, dtype, copy=True):
        dt = _resolve_dtype(dtype)
        if not copy and self._data.dtype == dt:
            return self
        return self._apply_unary(lambda x: x.astype(dt), "astype")

    # ------------------------------------------------------------- placement
    def as_in_context(self, ctx: Context) -> "NDArray":
        if self._lazy_cb is not None:
            self._lazy_materialize()
        if ctx == self.context:
            return self
        return NDArray(jax.device_put(self._data, ctx.jax_device()), ctx=ctx)

    as_in_ctx = as_in_context

    def copyto(self, other):
        """Copy into another NDArray (writes it) or onto a Context
        (reference: NDArray::CopyFromTo / ndarray.py copyto)."""
        if self._lazy_cb is not None:
            self._lazy_materialize()
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device()),
                           ctx=other)
        if isinstance(other, NDArray):
            other._set_data(jax.device_put(
                self._data.astype(other._data.dtype),
                other._data.device))
            return other
        raise MXNetError(f"copyto: unsupported target {type(other)}")

    def copy(self) -> "NDArray":
        if self._lazy_cb is not None:
            self._lazy_materialize()
        return NDArray(self._data, ctx=self._ctx)

    def detach(self) -> "NDArray":
        if self._lazy_cb is not None:
            self._lazy_materialize()
        out = NDArray(self._data, ctx=self._ctx)
        return out

    # -------------------------------------------------------------- autograd
    def attach_grad(self, grad_req: str = "write", stype=None):
        from .. import autograd
        with autograd.pause():
            self._grad = NDArray(jnp.zeros_like(self._data), ctx=self._ctx)
        self._grad_req = grad_req
        # attaching grad marks this array a leaf variable: cut upstream tape
        self._autograd_node = None

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def zero_grad(self):
        if self._grad is not None:
            from .. import autograd
            if autograd._STATE.pending is not None:
                autograd.flush_pending()  # grad write: flush deferred first
            self._grad._set_data(jnp.zeros_like(self._grad._data))

    # ------------------------------------------------------- generic dispatch
    def _apply_unary(self, fn, name):
        from ..ops.registry import OpDef, invoke
        op = OpDef(name, fn, 1, 1, True)
        return invoke(op, [self], {})

    def _op(self, name, *args, **kwargs):
        from . import op as _opmod
        return getattr(_opmod, name)(self, *args, **kwargs)

    # ------------------------------------------------------------ arithmetic
    def _binary(self, opname, scalar_opname, other, reverse=False):
        from . import op as _opmod
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return getattr(_opmod, opname)(a, b)
        if isinstance(other, (int, float, bool, np.number)):
            return getattr(_opmod, scalar_opname)(self, scalar=float(other))
        if isinstance(other, (np.ndarray, list, tuple)):
            other = NDArray(jnp.asarray(other), ctx=self._ctx)
            a, b = (other, self) if reverse else (self, other)
            return getattr(_opmod, opname)(a, b)
        return NotImplemented

    def __add__(self, o):
        return self._binary("broadcast_add", "_plus_scalar", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary("broadcast_sub", "_minus_scalar", o)

    def __rsub__(self, o):
        if isinstance(o, (int, float, np.number)):
            return self._op("_rminus_scalar", scalar=float(o))
        return self._binary("broadcast_sub", "_minus_scalar", o, reverse=True)

    def __mul__(self, o):
        return self._binary("broadcast_mul", "_mul_scalar", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary("broadcast_div", "_div_scalar", o)

    def __rtruediv__(self, o):
        if isinstance(o, (int, float, np.number)):
            return self._op("_rdiv_scalar", scalar=float(o))
        return self._binary("broadcast_div", "_div_scalar", o, reverse=True)

    def __mod__(self, o):
        return self._binary("broadcast_mod", "_mod_scalar", o)

    def __rmod__(self, o):
        if isinstance(o, (int, float, np.number)):
            return self._op("_rmod_scalar", scalar=float(o))
        return self._binary("broadcast_mod", "_mod_scalar", o, reverse=True)

    def __pow__(self, o):
        return self._binary("broadcast_power", "_power_scalar", o)

    def __rpow__(self, o):
        if isinstance(o, (int, float, np.number)):
            return self._op("_rpower_scalar", scalar=float(o))
        return NotImplemented

    def __neg__(self):
        return self._op("negative")

    def __abs__(self):
        return self._op("abs")

    def __matmul__(self, o):
        return self._op("dot", o)

    def __eq__(self, o):
        if o is None:
            return False
        return self._binary("broadcast_equal", "_equal_scalar", o)

    def __ne__(self, o):
        if o is None:
            return True
        return self._binary("broadcast_not_equal", "_not_equal_scalar", o)

    def __gt__(self, o):
        return self._binary("broadcast_greater", "_greater_scalar", o)

    def __ge__(self, o):
        return self._binary("broadcast_greater_equal",
                            "_greater_equal_scalar", o)

    def __lt__(self, o):
        return self._binary("broadcast_lesser", "_lesser_scalar", o)

    def __le__(self, o):
        return self._binary("broadcast_lesser_equal",
                            "_lesser_equal_scalar", o)

    def __hash__(self):
        return id(self)

    # in-place forms (reference: += dispatches with out=self)
    def __iadd__(self, o):
        res = self.__add__(o)
        self._set_data(res._data)
        self._autograd_node = res._autograd_node
        return self

    def __isub__(self, o):
        res = self.__sub__(o)
        self._set_data(res._data)
        self._autograd_node = res._autograd_node
        return self

    def __imul__(self, o):
        res = self.__mul__(o)
        self._set_data(res._data)
        self._autograd_node = res._autograd_node
        return self

    def __itruediv__(self, o):
        res = self.__truediv__(o)
        self._set_data(res._data)
        self._autograd_node = res._autograd_node
        return self

    # -------------------------------------------------------------- indexing
    def _normalize_index(self, key):
        if isinstance(key, NDArray):
            return key._data
        if isinstance(key, tuple):
            return tuple(self._normalize_index(k) for k in key)
        return key

    def __getitem__(self, key):
        key = self._normalize_index(key)
        from ..ops.registry import OpDef, invoke
        from .. import autograd
        if autograd.is_recording() and self._in_grad_graph():
            op = OpDef("getitem", lambda x: x[key], 1, 1, True)
            return invoke(op, [self], {})
        if self._lazy_cb is not None:
            self._lazy_materialize()
        return NDArray(self._data[key], ctx=self._ctx)

    def __setitem__(self, key, value):
        if self._lazy_cb is not None:
            self._lazy_materialize()
        key = self._normalize_index(key)
        if isinstance(value, NDArray):
            value = value._data
        if isinstance(key, slice) and key == slice(None):
            new = jnp.broadcast_to(jnp.asarray(value, dtype=self._data.dtype),
                                   self.shape)
        else:
            new = self._data.at[key].set(
                jnp.asarray(value, dtype=self._data.dtype))
        self._set_data(new)

    # ------------------------------------------------------------ repr/str
    def __repr__(self):
        try:
            arr = self.asnumpy()
            body = np.array2string(arr, separator=" ", prefix="")
        except Exception as e:  # show pending async error
            body = f"<error: {e}>"
        return f"\n{body}\n<NDArray {'x'.join(map(str, self.shape))} " \
               f"@{self.context}>"

    # --------------------------------------------------------- method sugar
    # (generated op methods are attached by ndarray.register at import)
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self._op("reshape", shape=shape, **kwargs)

    def reshape_like(self, other):
        return self.reshape(other.shape)

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return self._op("transpose", axes=axes)

    def flatten(self):
        return self._op("flatten")

    def expand_dims(self, axis):
        return self._op("expand_dims", axis=axis)

    def squeeze(self, axis=None):
        return self._op("squeeze", axis=axis)

    def sum(self, axis=None, keepdims=False):
        return self._op("sum", axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return self._op("mean", axis=axis, keepdims=keepdims)

    def prod(self, axis=None, keepdims=False):
        return self._op("prod", axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return self._op("max", axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return self._op("min", axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return self._op("argmax", axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return self._op("argmin", axis=axis, keepdims=keepdims)

    def norm(self, ord=2, axis=None, keepdims=False):
        return self._op("norm", ord=ord, axis=axis, keepdims=keepdims)

    def abs(self):
        return self._op("abs")

    def sqrt(self):
        return self._op("sqrt")

    def square(self):
        return self._op("square")

    def exp(self):
        return self._op("exp")

    def log(self):
        return self._op("log")

    def relu(self):
        return self._op("relu")

    def sigmoid(self):
        return self._op("sigmoid")

    def tanh(self):
        return self._op("tanh")

    def clip(self, a_min=None, a_max=None):
        return self._op("clip", a_min=a_min, a_max=a_max)

    def slice_axis(self, axis, begin, end):
        return self._op("slice_axis", axis=axis, begin=begin, end=end)

    def take(self, indices, axis=0, mode="clip"):
        return self._op("take", indices, axis=axis, mode=mode)

    def one_hot(self, depth, **kw):
        return self._op("one_hot", depth=depth, **kw)

    def tile(self, reps):
        return self._op("tile", reps=reps)

    def repeat(self, repeats, axis=None):
        return self._op("repeat", repeats=repeats, axis=axis)

    def flip(self, axis):
        return self._op("flip", axis=axis)

    def swapaxes(self, dim1, dim2):
        return self._op("swapaxes", dim1=dim1, dim2=dim2)

    def split(self, num_outputs, axis=1, squeeze_axis=False):
        return self._op("split", num_outputs=num_outputs, axis=axis,
                        squeeze_axis=squeeze_axis)

    def broadcast_to(self, shape):
        return self._op("broadcast_to", shape=shape)

    def broadcast_like(self, other):
        return self._op("broadcast_like", other)

    def topk(self, axis=-1, k=1, ret_typ="indices", is_ascend=False):
        return self._op("topk", axis=axis, k=k, ret_typ=ret_typ,
                        is_ascend=is_ascend)

    def sort(self, axis=-1, is_ascend=True):
        return self._op("sort", axis=axis, is_ascend=is_ascend)

    def argsort(self, axis=-1, is_ascend=True):
        return self._op("argsort", axis=axis, is_ascend=is_ascend)

    def dot(self, other, transpose_a=False, transpose_b=False):
        return self._op("dot", other, transpose_a=transpose_a,
                        transpose_b=transpose_b)

    def pad(self, mode="constant", pad_width=(), constant_value=0.0):
        return self._op("pad", mode=mode, pad_width=pad_width,
                        constant_value=constant_value)

    def tostype(self, stype):
        """Convert storage type (reference: NDArray.tostype); 'csr' and
        'row_sparse' live in ndarray/sparse.py."""
        if stype == "default":
            return self
        from . import sparse as _sparse
        return _sparse.tostype(self, stype)
