"""Process-wide runtime metrics registry: Counter / Gauge / Histogram.

The reference ships a profiler (``src/profiler/``) but no always-on
runtime counters; production serving stacks (TensorFlow runtime metrics,
TPU per-kernel accounting) need cheap process-wide counters that can be
scraped without attaching a tracer.  This module is that substrate: the
hot layers (op dispatch, engine, io, kvstore, trainer) publish into one
registry, and three exporters read it out:

- ``dump_prometheus()``  -> Prometheus text exposition format;
- ``chrome_counter_events()`` -> chrome-trace ``ph:"C"`` counter events,
  merged into ``profiler.dumps()`` so counters line up with host spans;
- ``dump_tensorboard()`` -> TensorBoard scalars via
  ``contrib.tensorboard.SummaryWriter``.

Overhead contract: metrics are **off by default**.  Every instrumentation
site guards on the module-level ``_ENABLED`` bool, so the disabled path
costs one attribute load + branch (~ns) per event — within noise on the
op-dispatch microbench.  Enable with ``MXNET_RUNTIME_METRICS=1`` or
``runtime_metrics.enable()``.  When enabled, mutation takes one small
per-metric lock (uncontended in the common single-writer case).
"""
from __future__ import annotations

import logging
import math
import os
import re
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from .base import MXNetError, env_truthy

_LOG = logging.getLogger("mxnet_tpu")

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "enable", "disable", "enabled",
    "reset", "snapshot", "dump_prometheus", "chrome_counter_events",
    "dump_tensorboard", "sample_memory", "record_op_invoke",
    "publish_grad_norm",
]

# fast-path switch read by every instrumentation site (module attribute
# load + branch — the whole disabled-path cost)
_ENABLED = env_truthy("MXNET_RUNTIME_METRICS", False)
# opt-in per-step grad-norm gauge: reading gradients forces a device
# sync, so it is gated separately from the cheap counters
_GRAD_NORM = env_truthy("MXNET_RUNTIME_METRICS_GRAD_NORM", False)


def enable():
    """Turn the registry on for this process (same as
    ``MXNET_RUNTIME_METRICS=1``)."""
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def grad_norm_enabled() -> bool:
    return _GRAD_NORM


_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
    """Canonical dotted names -> Prometheus metric names
    (``op.invoke`` -> ``op_invoke``)."""
    return _NAME_RE.sub("_", name)


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    iv = int(v)
    return str(iv) if v == iv else repr(float(v))


# per-metric bound on distinct label-value tuples: a call site that
# labels with a request-scoped value (user id, trace id, prompt...)
# would otherwise grow the registry without bound.  Beyond the bound,
# new label sets clamp into one overflow series and the metric warns
# ONCE — memory stays bounded, the misuse stays visible.
MAX_LABEL_SETS = 512
_OVERFLOW_LABEL = "__overflow__"


class _Metric:
    """Base: a named metric with optional label dimensions.

    Values are stored per label-value tuple; the unlabeled case is the
    empty tuple.  Each metric carries its own lock — mutation under it,
    export takes a consistent snapshot under it.
    """

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        # per-instance so tests (and unusual metrics) can tighten it
        self.max_label_sets = MAX_LABEL_SETS
        self._cardinality_warned = False

    def _store_key(self, store: dict,
                   key: Tuple[str, ...]) -> Tuple[str, ...]:
        """Cardinality guard — call with ``self._lock`` held.  A key
        already tracked passes through; a NEW key past the bound clamps
        to the shared overflow series (warning once), so per-request
        label misuse cannot grow memory without bound."""
        if not self.labelnames or key in store \
                or len(store) < self.max_label_sets:
            return key
        # mxlint: disable=atomicity (contract: callers hold self._lock,
        # per this method's docstring — the flag check-then-set is
        # already serialized; and the worst case is one extra warning)
        if not self._cardinality_warned:
            # mxlint: disable=lock-discipline (contract: callers hold
            # self._lock — every call site is inside `with self._lock`)
            self._cardinality_warned = True
            _LOG.warning(
                "metric %r exceeded %d distinct label sets — further "
                "new label values clamp into %s (per-request values do "
                "not belong in labels; put them in span tags via "
                "mxnet_tpu.tracing instead)",
                self.name, self.max_label_sets, _OVERFLOW_LABEL)
        return (_OVERFLOW_LABEL,) * len(self.labelnames)

    def _label_values(self, store, labelname):
        """Distinct recorded values of one label dimension, sorted —
        call via the subclass ``label_values`` (each owns its store).
        The enumeration a fleet sensor or doctor tool needs to sum a
        labeled family without touching private state."""
        try:
            i = self.labelnames.index(labelname)
        except ValueError:
            raise MXNetError(
                f"metric {self.name!r} has no label {labelname!r} "
                f"(labels: {self.labelnames})") from None
        with self._lock:
            return sorted({k[i] for k in store})

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if not self.labelnames:
            if labels:
                raise MXNetError(
                    f"metric {self.name!r} takes no labels, got {labels}")
            return ()
        try:
            return tuple(str(labels[k]) for k in self.labelnames)
        except KeyError as e:
            raise MXNetError(
                f"metric {self.name!r} requires labels "
                f"{self.labelnames}, got {sorted(labels)}") from e


class Counter(_Metric):
    """Monotonically increasing count (exported with a ``_total`` suffix)."""

    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1, **labels):
        # validate BEFORE the enabled check: a bad call site must fail
        # the same way whether or not the registry is switched on
        if amount < 0:
            raise MXNetError(f"counter {self.name!r}: negative increment")
        if not _ENABLED:
            return
        key = self._key(labels)
        with self._lock:
            key = self._store_key(self._values, key)
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def label_values(self, labelname):
        return self._label_values(self._values, labelname)

    def _snapshot(self):
        with self._lock:
            return dict(self._values)

    def _reset(self):
        with self._lock:
            self._values.clear()


class Gauge(_Metric):
    """A value that can go up and down (queue depth, live bytes, ...)."""

    kind = "gauge"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels):
        if not _ENABLED:
            return
        key = self._key(labels)
        with self._lock:
            key = self._store_key(self._values, key)
            self._values[key] = float(value)

    def set_max(self, value: float, **labels):
        """Keep the maximum seen (high-watermark gauges)."""
        if not _ENABLED:
            return
        key = self._key(labels)
        with self._lock:
            key = self._store_key(self._values, key)
            cur = self._values.get(key)
            if cur is None or value > cur:
                self._values[key] = float(value)

    def inc(self, amount: float = 1, **labels):
        if not _ENABLED:
            return
        key = self._key(labels)
        with self._lock:
            key = self._store_key(self._values, key)
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def label_values(self, labelname):
        return self._label_values(self._values, labelname)

    def _snapshot(self):
        with self._lock:
            return dict(self._values)

    def _reset(self):
        with self._lock:
            self._values.clear()


# default buckets cover host-side latencies (~us) through step times (~s)
DEFAULT_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics) with a
    bucket-interpolated ``quantile()`` reader and per-bucket
    **exemplars**: ``observe(v, exemplar=trace_id)`` remembers the most
    recent trace that landed in each bucket, so a scraped p99 links
    straight to the trace behind it (``exemplar_for_quantile``)."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=None):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bs:
            raise MXNetError(f"histogram {self.name!r}: empty buckets")
        self.buckets = bs
        # per label key: [[per-bucket counts..., +Inf count], sum,
        #                 count, [per-bucket (exemplar, value) | None]]
        self._data: Dict[Tuple[str, ...], list] = {}

    def observe(self, value: float, exemplar=None, **labels):
        """Record one observation.  ``exemplar`` (typically a
        ``tracing`` trace id) is attached to the bucket the value lands
        in — latest exemplar per bucket wins."""
        if not _ENABLED:
            return
        key = self._key(labels)
        v = float(value)
        with self._lock:
            key = self._store_key(self._data, key)
            entry = self._data.get(key)
            if entry is None:
                n = len(self.buckets) + 1
                entry = [[0] * n, 0.0, 0, [None] * n]
                self._data[key] = entry
            counts = entry[0]
            for i, b in enumerate(self.buckets):
                if v <= b:
                    break
            else:
                i = len(self.buckets)
            counts[i] += 1
            entry[1] += v
            entry[2] += 1
            if exemplar is not None:
                entry[3][i] = (str(exemplar), v)

    def count(self, **labels) -> int:
        with self._lock:
            entry = self._data.get(self._key(labels))
            return entry[2] if entry else 0

    def sum(self, **labels) -> float:
        with self._lock:
            entry = self._data.get(self._key(labels))
            return entry[1] if entry else 0.0

    def bucket_counts(self, **labels):
        """Cumulative per-bucket observation counts, aligned with
        ``buckets + (+Inf,)`` — a consistent snapshot.  The raw
        material for WINDOWED quantiles: diff two snapshots and feed
        the delta to an interpolator, so a control loop (the serving
        autoscaler's p99 sensor) reads the last interval instead of
        the process lifetime."""
        with self._lock:
            entry = self._data.get(self._key(labels))
            return list(entry[0]) if entry \
                else [0] * (len(self.buckets) + 1)

    def label_values(self, labelname):
        """Distinct recorded values of one label dimension, sorted —
        the enumeration a fleet sensor needs: replica-path engines
        observe under ``model="name/rid"`` while a direct engine uses
        ``model="name"``, and summing those series' ``bucket_counts``
        yields the set-wide distribution."""
        return self._label_values(self._data, labelname)

    def quantile(self, q: float, **labels) -> float:
        """Estimate the q-quantile by linear interpolation inside the
        bucket that crosses rank q*count (Prometheus histogram_quantile
        semantics).  Values beyond the last finite bucket clamp to it."""
        if not 0.0 <= q <= 1.0:
            raise MXNetError(f"quantile {q} outside [0, 1]")
        with self._lock:
            entry = self._data.get(self._key(labels))
            if entry is None or entry[2] == 0:
                return float("nan")
            counts, _, total = entry[0], entry[1], entry[2]
            rank = q * total
            cum = 0.0
            lo = 0.0
            for i, b in enumerate(self.buckets):
                prev = cum
                cum += counts[i]
                if cum >= rank:
                    frac = 0.0 if counts[i] == 0 else \
                        (rank - prev) / counts[i]
                    return lo + (b - lo) * frac
                lo = b
            return self.buckets[-1]

    def exemplars(self, **labels):
        """Per-bucket ``(exemplar, value)`` pairs (None where no
        exemplar landed), aligned with ``buckets + (+Inf,)``."""
        with self._lock:
            entry = self._data.get(self._key(labels))
            return list(entry[3]) if entry else \
                [None] * (len(self.buckets) + 1)

    def exemplar_for_quantile(self, q: float, **labels):
        """The exemplar (trace id) nearest the q-quantile: the bucket
        the quantile falls in, else the closest populated neighbor
        (higher buckets first — for a p99 you want the slower trace).
        Returns the exemplar string, or None."""
        if not 0.0 <= q <= 1.0:
            raise MXNetError(f"quantile {q} outside [0, 1]")
        with self._lock:
            entry = self._data.get(self._key(labels))
            if entry is None or entry[2] == 0:
                return None
            counts, _, total, exemplars = entry
            rank = q * total
            cum = 0.0
            idx = len(counts) - 1
            for i, c in enumerate(counts):
                cum += c
                if cum >= rank:
                    idx = i
                    break
            for i in list(range(idx, len(exemplars))) + \
                    list(range(idx - 1, -1, -1)):
                if exemplars[i] is not None:
                    return exemplars[i][0]
            return None

    def _snapshot(self):
        with self._lock:
            return {k: (list(e[0]), e[1], e[2])
                    for k, e in self._data.items()}

    def _snapshot_exemplars(self):
        with self._lock:
            return {k: list(e[3]) for k, e in self._data.items()}

    def _reset(self):
        with self._lock:
            self._data.clear()


class MetricsRegistry:
    """Get-or-create store of named metrics (process-wide singleton at
    ``runtime_metrics.REGISTRY``)."""

    def __init__(self):
        self._metrics: "OrderedDict[str, _Metric]" = OrderedDict()
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help, labelnames=labelnames, **kwargs)
                self._metrics[name] = m
                return m
        if not isinstance(m, cls):
            raise MXNetError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}")
        if tuple(labelnames) != m.labelnames:
            raise MXNetError(
                f"metric {name!r} registered with labels {m.labelnames}, "
                f"requested {tuple(labelnames)}")
        if cls is Histogram and kwargs.get("buckets") is not None:
            want = tuple(sorted(float(b) for b in kwargs["buckets"]))
            if want != m.buckets:
                raise MXNetError(
                    f"histogram {name!r} registered with buckets "
                    f"{m.buckets}, requested {want}")
        return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def reset(self):
        """Zero every metric's samples (registrations survive — module
        handles like ``OP_INVOKE`` stay valid).  Test/tool helper."""
        for m in self.collect():
            m._reset()


REGISTRY = MetricsRegistry()


def counter(name, help="", labelnames=()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets)


def reset():
    REGISTRY.reset()


def snapshot() -> Dict[str, dict]:
    """Plain-dict view {name: {"type", "labels", "values"}} for tooling
    (tools/diagnose.py)."""
    _run_collect_hooks()
    out = {}
    for m in REGISTRY.collect():
        if m.kind == "histogram":
            values = {",".join(k) or "": {"count": e[2], "sum": e[1]}
                      for k, e in m._snapshot().items()}
        else:
            values = {",".join(k) or "": v
                      for k, v in m._snapshot().items()}
        out[m.name] = {"type": m.kind, "labels": m.labelnames,
                       "values": values}
    return out


# ---------------------------------------------------------------------------
# Exporters
# ---------------------------------------------------------------------------

def _escape_label(v: str) -> str:
    """Prometheus exposition label-value escaping (backslash, quote,
    newline) — label values are arbitrary user strings (model names)."""
    return v.replace("\\", "\\\\").replace('"', '\\"') \
            .replace("\n", "\\n")


def _label_str(labelnames, key) -> str:
    if not labelnames:
        return ""
    pairs = ",".join(f'{n}="{_escape_label(v)}"'
                     for n, v in zip(labelnames, key))
    return "{" + pairs + "}"


# Gauges that are cheapest to refresh at scrape time (rather than on
# every mutation of the underlying structure) register a collect hook;
# every exporter runs them first.
_COLLECT_HOOKS: List = []
_COLLECT_HOOKS_LOCK = threading.Lock()


def register_collect_hook(fn):
    with _COLLECT_HOOKS_LOCK:
        _COLLECT_HOOKS.append(fn)


def _run_collect_hooks():
    for fn in list(_COLLECT_HOOKS):
        try:
            fn()
        except Exception:       # noqa: BLE001 — exporters must not die
            pass


def dump_prometheus() -> str:
    """Serialize every metric in the Prometheus text exposition format.
    Counters get the conventional ``_total`` suffix; histograms render
    cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``."""
    _run_collect_hooks()
    lines = []
    for m in REGISTRY.collect():
        base = _sanitize(m.name)
        if m.kind == "counter":
            base += "_total"
        if m.help:
            lines.append(f"# HELP {base} {m.help}")
        lines.append(f"# TYPE {base} {m.kind}")
        if m.kind in ("counter", "gauge"):
            snap = m._snapshot()
            if not snap and not m.labelnames:
                snap = {(): 0.0}
            for key in sorted(snap):
                lines.append(
                    f"{base}{_label_str(m.labelnames, key)} "
                    f"{_fmt(snap[key])}")
        else:  # histogram
            snap = m._snapshot()
            exs = m._snapshot_exemplars()

            def _ex(key, i):
                # OpenMetrics exemplar suffix: the bucket's most recent
                # trace id, so a scraped p99 resolves to a trace
                e = exs.get(key)
                if not e or e[i] is None:
                    return ""
                tid, v = e[i]
                return (f' # {{trace_id="{_escape_label(tid)}"}} '
                        f"{_fmt(v)}")

            for key in sorted(snap):
                counts, total, n = snap[key]
                cum = 0
                for i, b in enumerate(m.buckets):
                    cum += counts[i]
                    lbl = _label_str(m.labelnames + ("le",),
                                     key + (_fmt(b),))
                    lines.append(f"{base}_bucket{lbl} {cum}"
                                 f"{_ex(key, i)}")
                cum += counts[-1]
                lbl = _label_str(m.labelnames + ("le",), key + ("+Inf",))
                lines.append(f"{base}_bucket{lbl} {cum}"
                             f"{_ex(key, len(m.buckets))}")
                ls = _label_str(m.labelnames, key)
                lines.append(f"{base}_sum{ls} {_fmt(total)}")
                lines.append(f"{base}_count{ls} {n}")
    return "\n".join(lines) + "\n"


def chrome_counter_events(t0_us: float = 0.0) -> List[dict]:
    """Snapshot every metric as chrome-trace ``ph:"C"`` counter events
    (one event per metric; labeled series become one arg per label set).
    ``profiler.dumps()`` merges these into the host-span trace so
    counters share the timeline with op/user scopes."""
    _run_collect_hooks()
    ts = time.perf_counter() * 1e6 - t0_us
    pid = os.getpid()
    events = []
    for m in REGISTRY.collect():
        if m.kind == "histogram":
            args = {}
            for key, (counts, total, n) in sorted(m._snapshot().items()):
                tag = ",".join(key) or "all"
                args[f"{tag}.count"] = n
                args[f"{tag}.sum"] = total
        else:
            snap = m._snapshot()
            args = {",".join(key) or m.name: v
                    for key, v in sorted(snap.items())}
        if not args:
            continue
        events.append({"name": m.name, "ph": "C", "ts": ts, "pid": pid,
                       "args": args})
    return events


def dump_tensorboard(logdir=None, writer=None, step=None):
    """Write every metric as TensorBoard scalars (counters/gauges one
    scalar per label set; histograms as ``.count``/``.sum``/``.mean``).
    Pass an open ``SummaryWriter`` to reuse one event file across steps,
    or a ``logdir`` to write-and-close in one call."""
    from .contrib.tensorboard import SummaryWriter
    own = False
    if writer is None:
        if logdir is None:
            raise MXNetError("dump_tensorboard: pass logdir= or writer=")
        writer = SummaryWriter(logdir)
        own = True
    try:
        for m in REGISTRY.collect():
            if m.kind == "histogram":
                for key, (counts, total, n) in m._snapshot().items():
                    tag = m.name + ("." + ".".join(key) if key else "")
                    writer.add_scalar(tag + ".count", n, step)
                    writer.add_scalar(tag + ".sum", total, step)
                    if n:
                        writer.add_scalar(tag + ".mean", total / n, step)
            else:
                for key, v in m._snapshot().items():
                    tag = m.name + ("." + ".".join(key) if key else "")
                    writer.add_scalar(tag, v, step)
    finally:
        if own:
            writer.close()
        else:
            writer.flush()


# ---------------------------------------------------------------------------
# Pre-declared instruments for the built-in instrumentation sites.
# Call sites guard on `_ENABLED` before touching these.
# ---------------------------------------------------------------------------

OP_INVOKE = counter(
    "op.invoke", "Imperative op invocations via ops.registry.invoke.",
    labelnames=("op",))
OP_DISPATCH_SECONDS = histogram(
    "op.dispatch.seconds",
    "Host-side dispatch latency per imperative op call (dispatch + "
    "trace cost, not device occupancy).", labelnames=("op",))
ENGINE_WAITALL = counter(
    "engine.waitall", "waitall() full-sync points.")
ENGINE_WAITALL_SECONDS = histogram(
    "engine.waitall.seconds", "Time blocked inside waitall().")
ENGINE_TRACKED = gauge(
    "engine.tracked_arrays",
    "Live NDArrays currently tracked by the engine.")
ENGINE_TRACKED_PEAK = gauge(
    "engine.tracked_arrays.peak",
    "High watermark of engine-tracked NDArrays.")
IO_BATCHES = counter(
    "io.batches", "Batches produced by data iterators.")
IO_NATIVE_DECODE = counter(
    "io.decode.native", "Images decoded by the native C++ JPEG tier.")
IO_PYTHON_DECODE = counter(
    "io.decode.python", "Images decoded on the Python/cv2 fallback path.")
IO_PREFETCH_DEPTH = gauge(
    "io.prefetch.depth",
    "Prefetch queue depth observed at the last consumer read.")
KV_PUSH = counter("kvstore.push", "kvstore push() calls (per key).")
KV_PUSH_BYTES = counter(
    "kvstore.push.bytes",
    "LOGICAL (uncompressed, shape x itemsize) bytes pushed into the "
    "kvstore — the application-level gradient volume, NOT wire "
    "traffic; see kvstore.wire.bytes for what actually crosses the "
    "interconnect.")
KV_PULL = counter("kvstore.pull", "kvstore pull() calls (per key).")
KV_PULL_BYTES = counter(
    "kvstore.pull.bytes",
    "LOGICAL (uncompressed) bytes copied out of the kvstore by pull() "
    "— application-level volume, not wire traffic.")
KV_WIRE_BYTES = counter(
    "kvstore.wire.bytes",
    "Gradient-sync payload bytes that actually cross the interconnect "
    "(push-direction accounting, per device copy): equals the logical "
    "push volume for uncompressed collectives, and the compressed "
    "payload + per-block-scale size under int8/fp8 gradient "
    "compression (kvstore.set_gradient_compression / "
    "MXNET_KVSTORE_GRAD_COMPRESSION; ShardedTrainer(compression=...) "
    "counts its quantized dp-allreduce here too).  "
    "wire.bytes / push.bytes is the live compression ratio.")
TRAINER_STEP_SECONDS = histogram(
    "trainer.step.seconds",
    "Wall-clock time of one optimizer step (gluon.Trainer.step / "
    "Module fit batch).")
TRAINER_GRAD_NORM = gauge(
    "trainer.grad_norm",
    "Global L2 gradient norm after the last step "
    "(MXNET_RUNTIME_METRICS_GRAD_NORM=1 to enable sampling).")
TRAINER_SAMPLES_PER_SEC = gauge(
    "trainer.samples_per_sec",
    "Training throughput published by callback.Speedometer.")
TRAIN_RESTARTS = counter(
    "train.restarts",
    "TrainingSupervisor restore+restart cycles after a transient "
    "train-loop failure (injected kill, step timeout, device blip).  "
    "Under a chaos plan this must equal the injected kill count.")
TRAIN_RECOVERY_SECONDS = histogram(
    "train.recovery.seconds",
    "Wall-clock cost of one supervised recovery: checkpoint restore + "
    "RNG/data-cursor rewind, from failure acceptance to the loop "
    "being ready to re-step (backoff sleep excluded).")
TRAIN_STEP_TIMEOUTS = counter(
    "train.step.timeouts",
    "ShardedTrainer steps killed by the MXNET_TRAIN_STEP_TIMEOUT_MS "
    "watchdog deadline (wedged collective / stuck device) — each one "
    "raised a TrainStepTimeoutError instead of hanging the loop.")
TRAIN_SLOW_STEPS = counter(
    "train.slow_steps",
    "Straggler steps: watched step time exceeded "
    "MXNET_TRAIN_SLOW_STEP_FACTOR x the rolling median (flight-"
    "recorder incident dumped per detection).")
TRAIN_STEP_BREAKDOWN_SECONDS = histogram(
    "train.step.breakdown.seconds",
    "Per-phase decomposition of one attributed ShardedTrainer step "
    "(perf_account.StepAttribution): data_wait (iterator next + host "
    "staging), h2d (device transfer), compute (dispatch -> device "
    "completion of the fused step program), collective and optimizer "
    "(0s markers — both run fused inside the compute program; the "
    "span tags carry wire-vs-logical bytes).  Phases tile the "
    "train.step span interval.", labelnames=("phase",))
TRAIN_MFU = gauge(
    "train.mfu",
    "Model FLOPs utilization over the attribution window: XLA "
    "cost_analysis FLOPs of the compiled step / measured step time / "
    "per-chip peak (MXNET_PEAK_TFLOPS or device-kind default).  0 "
    "when the backend exposes no cost analysis.")
TRAIN_BOTTLENECK = gauge(
    "train.bottleneck",
    "Windowed bottleneck verdict from the step breakdown: 0 "
    "compute_bound, 1 input_bound (data_wait + h2d dominate), 2 "
    "comm_bound (collective dominates).  A non-compute verdict "
    "requires its phases to reach the StepAttribution threshold "
    "(default 25%) of windowed wall time.")
MEMORY_LIVE_BYTES = gauge(
    "memory.live_bytes",
    "Live accelerator bytes per device (host RSS fallback when the "
    "backend reports no memory_stats).", labelnames=("device",))
ENGINE_SYNC_SECONDS = histogram(
    "engine.sync.seconds",
    "Time blocked in bounded sync points (engine.sync_outputs: one "
    "dispatched batch, not the whole pipeline), labeled by call site.",
    labelnames=("site",))
SERVING_REQUESTS = counter(
    "serving.requests", "Requests admitted by ModelServer.predict.",
    labelnames=("model",))
SERVING_BATCHES = counter(
    "serving.batches", "Coalesced batches dispatched by the serving "
    "worker pool.", labelnames=("model",))
SERVING_SHED = counter(
    "serving.shed",
    "Requests rejected with ServerOverloadedError because the bounded "
    "queue sat at/above the load-shedding watermark.",
    labelnames=("model",))
SERVING_QUEUE_DEPTH = gauge(
    "serving.queue.depth",
    "Requests currently waiting in the ModelServer bounded queue "
    "(all models), per server instance.", labelnames=("server",))
SERVING_QUEUE_PEAK = gauge(
    "serving.queue.depth.peak",
    "High watermark of the serving queue depth, per server instance.",
    labelnames=("server",))
# occupancy = real rows / padded bucket rows — 1.0 means no padding waste
SERVING_BATCH_OCCUPANCY = histogram(
    "serving.batch.occupancy",
    "Real rows divided by padded bucket rows per dispatched batch "
    "(1.0 = no padding waste).",
    buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0))
SERVING_REQUEST_SECONDS = histogram(
    "serving.request.seconds",
    "End-to-end request latency inside ModelServer (enqueue to result "
    "ready), per model.", labelnames=("model",))
SERVING_BUCKET_CACHE = counter(
    "serving.bucket.cache",
    "Shape-bucket program-cache lookups by the serving batcher "
    "(event=mem_hit|disk_hit|miss; misses equal freshly COMPILED "
    "programs, disk hits are executables deserialized from the "
    "persistent compile cache, and mem_hit+disk_hit+miss equals "
    "lookups — so in-memory programs == misses + disk hits).",
    labelnames=("event",))
SERVING_DECODE_STEPS = counter(
    "serving.decode.steps",
    "Scheduler iterations of the continuous-batching decode engine "
    "(admit -> prefill -> one decode step -> evict), per model.",
    labelnames=("model",))
SERVING_DECODE_TOKENS = counter(
    "serving.decode.tokens",
    "Tokens generated by the decode engine (prefill first tokens + "
    "decode-step tokens), per model.", labelnames=("model",))
SERVING_DECODE_EVICTIONS = counter(
    "serving.decode.evictions",
    "Sequences evicted from the decode batch (finished, cancelled, or "
    "failed) with their KV pages returned to the free list, per model.",
    labelnames=("model",))
SERVING_DECODE_TTFT_SECONDS = histogram(
    "serving.decode.ttft.seconds",
    "Time to first token: generate() submission to the first sampled "
    "token (queueing + prefill), per model.", labelnames=("model",))
SERVING_DECODE_TOKEN_SECONDS = histogram(
    "serving.decode.token.seconds",
    "Per-token decode latency (time between consecutive sampled tokens "
    "of one sequence), per model.", labelnames=("model",))
SERVING_DECODE_KV_OCCUPANCY = gauge(
    "serving.decode.kv.occupancy",
    "Used fraction of the paged KV cache pool (allocated pages / "
    "usable pages), per decode engine.", labelnames=("engine",))
SERVING_PREFIX_HITS = counter(
    "serving.decode.prefix.hits",
    "Prompts admitted with a prefix-cache hit (>= 1 full page of "
    "prompt K/V aliased from the radix tree instead of prefilled), "
    "per model (docs/serving.md §9).", labelnames=("model",))
SERVING_PREFIX_MISSES = counter(
    "serving.decode.prefix.misses",
    "Prefix-cache lookups that matched nothing (the prompt prefilled "
    "in full, then seeded the cache), per model.  hits/(hits+misses) "
    "is the live hit ratio.", labelnames=("model",))
SERVING_PREFIX_TOKENS_SAVED = counter(
    "serving.decode.prefix.tokens_saved",
    "Prompt tokens whose prefill was skipped by prefix-cache hits "
    "(matched tokens minus the one re-run token of a full hit), per "
    "model — the TTFT work the cache removed.", labelnames=("model",))
SERVING_SPEC_PROPOSED = counter(
    "serving.decode.spec.proposed",
    "Draft tokens proposed by speculative decoding, per model "
    "(docs/serving.md §9).", labelnames=("model",))
SERVING_SPEC_ACCEPTED = counter(
    "serving.decode.spec.accepted",
    "Draft tokens accepted by target verification, per model.  "
    "accepted/proposed is the draft acceptance rate; each round also "
    "emits one non-speculative (correction or bonus) token.",
    labelnames=("model",))
KV_SHARED_PAGES = gauge(
    "kv.shared_pages",
    "KV pages currently referenced more than once (shared between "
    "sequences and/or the prefix cache) in a decode engine's paged "
    "pool, per engine.", labelnames=("engine",))
SERVING_FAULTS = counter(
    "serving.faults",
    "Faults fired by the active fault-injection plan "
    "(mxnet_tpu.faults, MXNET_FAULTS), labeled by injection site and "
    "mode (fail|delay|corrupt|stall).",
    labelnames=("site", "mode"))
SERVING_RETRIES = counter(
    "serving.retries",
    "Transient-failure retries on the serving execute paths (coalesced "
    "batch re-execution, decode prefill/step re-execution), per model.",
    labelnames=("model",))
SERVING_DEADLINE_EXCEEDED = counter(
    "serving.deadline_exceeded",
    "Requests failed by end-to-end deadline expiry (in the queue, at "
    "batch assembly, or mid-generation), per model.",
    labelnames=("model",))
SERVING_CIRCUIT_STATE = gauge(
    "serving.circuit.state",
    "Per-model-version circuit-breaker state: 0 closed, 1 half-open, "
    "2 open (serving.resilience.CircuitBreaker).",
    labelnames=("model", "version"))
SERVING_DECODE_QUARANTINED = counter(
    "serving.decode.quarantined",
    "Sequences evicted alone after a decode/prefill step failure was "
    "bisected down to them (pages reclaimed, batchmates keep "
    "decoding), per model.", labelnames=("model",))
SERVING_REPLICA_STATE = gauge(
    "serving.replica.state",
    "Replica lifecycle state per (model, replica): 0 starting, "
    "1 prewarming, 2 healthy, 3 unhealthy, 4 draining, 5 stopped "
    "(serving.replica.ReplicaSet, docs/serving.md §10).  Only state 2 "
    "is routable.", labelnames=("model", "replica"))
SERVING_REPLICA_REQUESTS = counter(
    "serving.replica.requests",
    "Requests dispatched to one replica (predict batches + generate "
    "submissions), per (model, replica) — compare across replicas for "
    "the live load balance.", labelnames=("model", "replica"))
SERVING_REPLICA_FAILOVERS = counter(
    "serving.replica.failovers",
    "Requests rerouted to a sibling replica after their first replica "
    "failed (typed execute failure, quarantine, or engine stop), per "
    "model.  Every failed-over request keeps its ORIGINAL end-to-end "
    "deadline.", labelnames=("model",))
SERVING_AUTOSCALE_DECISIONS = counter(
    "serving.autoscale.decisions",
    "Autoscaler control-loop decisions per tick "
    "(serving.autoscaler.Autoscaler, docs/serving.md §11), per "
    "(model, action): up/down actuated a replica change, hold stayed, "
    "blocked hit the max-replica budget or a cooldown, error had the "
    "actuator raise (the loop stays alive and backs off).",
    labelnames=("model", "action"))
SERVING_AUTOSCALE_REPLICAS_TARGET = gauge(
    "serving.autoscale.replicas_target",
    "Replica count the autoscaler last decided the model should run "
    "at — compare against serving.replica.state for actual vs target.",
    labelnames=("model",))
SERVING_TENANT_REQUESTS = counter(
    "serving.tenant.requests",
    "Requests ADMITTED by the tiered admission gate "
    "(serving.admission.AdmissionController, docs/serving.md §11), "
    "per (tenant, tier) — under the label-cardinality guard, so an "
    "unbounded tenant id space clamps into the overflow series "
    "instead of growing memory.", labelnames=("tenant", "tier"))
SERVING_TENANT_SHED = counter(
    "serving.tenant.shed",
    "Requests shed by the tiered admission gate (tenant over its "
    "quota token bucket, or its tier priority-shed under overload "
    "pressure — low tier first), per (tenant, tier).  Every shed is "
    "a typed ServerOverloadedError with a retry-after hint.",
    labelnames=("tenant", "tier"))
SERVING_REPLICA_HEARTBEAT_AGE = gauge(
    "serving.replica.heartbeat_age",
    "Seconds since one replica's last heartbeat, per (model, replica) "
    "— updated on every beat and on every health sweep; ages past "
    "MXNET_SERVING_REPLICA_HEARTBEAT_WINDOW_MS mark the replica "
    "UNHEALTHY.", labelnames=("model", "replica"))
COMPILE_CACHE = counter(
    "compile.cache",
    "Persistent compiled-executable cache events "
    "(mxnet_tpu.compile_cache): event=hit|miss|corrupt|store|evict for "
    "the serving executable store, jax_hit|jax_miss for jax's own "
    "persistent compilation cache when routed via "
    "enable_jax_persistent_cache.",
    labelnames=("event",))
COMPILE_CACHE_DESERIALIZE_SECONDS = histogram(
    "compile.cache.deserialize.seconds",
    "Time to deserialize + load one cached executable onto the current "
    "devices (the disk-hit replacement for an XLA compile).")


def record_op_invoke(opname: str, seconds: float):
    """One-call hot-path helper for ops/registry.invoke."""
    OP_INVOKE.inc(op=opname)
    OP_DISPATCH_SECONDS.observe(seconds, op=opname)


def publish_grad_norm(grads) -> Optional[float]:
    """Global L2 norm over an iterable of gradient NDArrays -> the
    ``trainer.grad_norm`` gauge (shared by gluon.Trainer and Module).
    Reads gradients to the host — a device sync — which is why callers
    gate on ``grad_norm_enabled()``.  Returns the norm, or None (gauge
    untouched) when any gradient is unreadable."""
    total = 0.0
    try:
        for g in grads:
            a = g.asnumpy()
            total += float((a.astype("float64") ** 2).sum())
    except Exception:       # noqa: BLE001 — no grad yet / failed husk
        return None
    norm = math.sqrt(total)
    TRAINER_GRAD_NORM.set(norm)
    return norm


# ---------------------------------------------------------------------------
# Memory sampling (profiler profile_memory backend)
# ---------------------------------------------------------------------------

def _host_rss_bytes() -> float:
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:       # noqa: BLE001 — non-linux fallback
        try:
            import resource
            return float(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024)
        except Exception:   # noqa: BLE001
            return 0.0


def sample_memory() -> List[Tuple[str, float, Optional[float]]]:
    """Sample per-device live bytes into the ``memory.live_bytes`` gauge.

    Returns ``[(device_label, live_bytes, bytes_limit_or_None), ...]``
    regardless of whether the registry is enabled, so the profiler can
    emit its own counter events (``profile_memory=True``) even with
    metrics off.  Devices that report no ``memory_stats`` (CPU backend)
    fall back to one host-RSS sample labeled ``host``.
    """
    stats = []
    try:
        import jax
        for d in jax.devices():
            try:
                ms = d.memory_stats()
            except Exception:       # noqa: BLE001 — backend w/o stats
                ms = None
            if ms and ms.get("bytes_in_use") is not None:
                stats.append((f"{d.platform}:{d.id}",
                              float(ms["bytes_in_use"]),
                              float(ms["bytes_limit"])
                              if ms.get("bytes_limit") else None))
    except Exception:               # noqa: BLE001 — jax unavailable
        pass
    if not stats:
        stats = [("host", _host_rss_bytes(), None)]
    if _ENABLED:
        for dev, used, _limit in stats:
            MEMORY_LIVE_BYTES.set(used, device=dev)
    return stats
