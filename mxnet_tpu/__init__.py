"""mxnet_tpu: a TPU-native deep-learning framework with MXNet's capabilities.

A brand-new framework (not a port) built on JAX/XLA/PJRT for TPU, providing
the capability surface of Apache (incubator-)MXNet v1.x — async NDArray
runtime, autograd, Gluon Block/HybridBlock/Trainer, symbolic graphs +
executors, declarative op registry, kvstore distributed API over XLA
collectives, data pipelines, profiler/metric/checkpoint subsystems.
See SURVEY.md at the repo root for the blueprint.

Import convention mirrors the reference::

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu(0))
"""

__version__ = "0.1.0"

from . import base
from .base import MXNetError
from . import context
from .context import Context, cpu, gpu, tpu, cpu_pinned, current_context, \
    num_gpus, num_tpus
from . import engine
from . import autograd
from . import ndarray
from . import ndarray as nd
from . import random
from . import symbol
from . import symbol as sym
from .ndarray import NDArray
from .symbol import Symbol
from . import attribute
from .attribute import AttrScope


def waitall():
    """Block until all async computation completes (mx.nd.waitall)."""
    engine.waitall()


# Subsystems below are imported lazily-but-eagerly as they land in the build.
import importlib as _importlib

for _mod in ("initializer", "optimizer", "metric", "gluon", "io", "kvstore",
             "recordio", "callback", "profiler", "runtime_metrics",
             "tracing", "monitor", "util", "runtime",
             "test_utils", "executor", "module", "image", "contrib",
             "parallel", "models", "np", "npx", "lr_scheduler", "operator",
             "library", "subgraph", "deploy", "serving", "quantize"):
    try:
        globals()[_mod] = _importlib.import_module(f".{_mod}", __name__)
    except ModuleNotFoundError as _e:
        # tolerate only "module not built yet", never a broken module
        if _e.name != f"{__name__}.{_mod}":
            raise

if "initializer" in globals():
    init = getattr(initializer, "init", initializer)  # noqa: F821
if "kvstore" in globals():
    kv = kvstore  # noqa: F821
