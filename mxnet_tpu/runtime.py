"""Runtime feature detection (reference: python/mxnet/runtime.py over
src/libinfo.cc compile-time feature bits)."""
from __future__ import annotations

from typing import Dict

__all__ = ["Features", "feature_list"]


class Feature:
    def __init__(self, name: str, enabled: bool):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _pallas_enabled() -> bool:
    try:
        from .ops.pallas_kernels import pallas_available
        return pallas_available()
    except Exception:
        return False


def _detect() -> Dict[str, bool]:
    import jax
    feats = {
        "TPU": any(d.platform != "cpu" for d in jax.devices()),
        "XLA": True,
        "PJRT": True,
        "CUDA": False,          # by design: no CUDA in the build
        "CUDNN": False,
        "MKLDNN": False,
        "OPENCV": False,
        "DIST_KVSTORE": True,   # xla collectives backend
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": True,
        "PALLAS": _pallas_enabled(),
        "BF16": True,
        "INT8_QUANTIZATION": True,   # ops/quantization.py int8 MXU path
        "NATIVE_IO": False,     # flipped true when the C++ recordio lib loads
    }
    try:
        from .lib import nativelib
        feats["NATIVE_IO"] = nativelib.available()
    except Exception:
        pass
    return feats


class Features(dict):
    """mx.runtime.Features() (reference: runtime.py)."""

    def __init__(self):
        super().__init__({k: Feature(k, v) for k, v in _detect().items()})

    def is_enabled(self, name: str) -> bool:
        f = self.get(name)
        return bool(f and f.enabled)


def feature_list():
    return list(Features().values())
