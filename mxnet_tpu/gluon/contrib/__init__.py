"""Gluon contrib (reference: python/mxnet/gluon/contrib/)."""
from . import estimator
from . import nn
from . import detection, rnn
from .fused import FusedTrainStep
from .moe import MoEFFN

__all__ = ["detection", "estimator", "nn", "rnn",
           "FusedTrainStep", "MoEFFN"]
