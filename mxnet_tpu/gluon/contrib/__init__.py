"""Gluon contrib (reference: python/mxnet/gluon/contrib/)."""
from . import estimator

__all__ = ["estimator"]
