"""Gluon contrib (reference: python/mxnet/gluon/contrib/)."""
from . import estimator
from . import nn
from . import rnn

__all__ = ["estimator", "nn", "rnn"]
