"""Gluon contrib (reference: python/mxnet/gluon/contrib/)."""
from . import estimator
from . import nn
from . import rnn
from .fused import FusedTrainStep
from .moe import MoEFFN

__all__ = ["estimator", "nn", "rnn", "FusedTrainStep", "MoEFFN"]
