"""Two-stage detection building blocks: FPN, RPN, Faster R-CNN.

Reference surface: GluonCV ``model_zoo/fpn``/``model_zoo/faster_rcnn``
(the sibling library the reference ecosystem shipped detection in;
upstream MXNet itself carries the op layer — ROIAlign
``src/operator/contrib/roi_align.cc``, proposal/box ops — that these
heads are built from, SURVEY.md §2.1 contrib ops).

TPU-first redesign: everything is STATIC-SHAPE.  Proposal selection is
``lax.top_k`` + a fixed-iteration mask-based NMS (no dynamic box
counts, no data-dependent shapes — the XLA-compilable equivalent of
GluonCV's dynamic ``box_nms``); ROI sampling for training picks the
top-scoring positives/negatives rather than random subsets, so one
compiled program serves every step.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock

__all__ = ["FPN", "AnchorGenerator", "RPNHead", "box_iou",
           "decode_deltas", "encode_deltas", "nms_static",
           "fpn_level_index", "RCNNBoxHead", "FasterRCNN"]


class FPN(HybridBlock):
    """Feature Pyramid Network neck (GluonCV ``FPNFeatureExpander``):
    lateral 1x1 on each backbone stage, top-down nearest upsample, 3x3
    smoothing; highest level optionally downsampled to P6."""

    def __init__(self, in_channels, channels=256, use_p6=True, **kwargs):
        super().__init__(**kwargs)
        self._n = len(in_channels)
        self._use_p6 = use_p6
        with self.name_scope():
            self.laterals = nn.HybridSequential()
            self.smooths = nn.HybridSequential()
            for c in in_channels:
                self.laterals.add(nn.Conv2D(channels, 1, in_channels=c))
                self.smooths.add(nn.Conv2D(channels, 3, padding=1,
                                           in_channels=channels))

    def hybrid_forward(self, F, *feats):
        if len(feats) != self._n:
            raise MXNetError(f"FPN expects {self._n} feature maps, "
                             f"got {len(feats)}")
        laterals = [lat(x) for lat, x in zip(self.laterals, feats)]
        outs = [laterals[-1]]
        for lvl in range(self._n - 2, -1, -1):
            up = F.UpSampling(outs[0], scale=2, sample_type="nearest",
                              num_args=1)
            # crop in case the lower level has odd spatial dims
            up = F.slice_like(up, laterals[lvl], axes=(2, 3))
            outs.insert(0, laterals[lvl] + up)
        outs = [sm(x) for sm, x in zip(self.smooths, outs)]
        if self._use_p6:
            outs.append(F.Pooling(outs[-1], kernel=(2, 2), stride=(2, 2),
                                  pool_type="max"))
        return tuple(outs)


class AnchorGenerator:
    """Dense grid anchors per pyramid level, corner (x1,y1,x2,y2) in
    pixels (GluonCV ``RPNAnchorGenerator``)."""

    def __init__(self, strides, sizes, ratios=(0.5, 1.0, 2.0)):
        if len(strides) != len(sizes):
            raise MXNetError("strides and sizes must align per level")
        self.strides = tuple(strides)
        self.sizes = tuple(sizes)
        self.ratios = tuple(ratios)
        self.num_anchors = len(ratios)

    def level(self, lvl, H, W):
        """(H*W*num_ratios, 4) numpy anchors for one level."""
        stride, size = self.strides[lvl], self.sizes[lvl]
        ws = np.array([size * np.sqrt(1.0 / r) for r in self.ratios])
        hs = np.array([size * np.sqrt(r) for r in self.ratios])
        cx = (np.arange(W) + 0.5) * stride
        cy = (np.arange(H) + 0.5) * stride
        cxg, cyg = np.meshgrid(cx, cy)                  # (H, W)
        ctrs = np.stack([cxg, cyg], axis=-1).reshape(-1, 1, 2)
        wh = np.stack([ws, hs], axis=-1).reshape(1, -1, 2)
        boxes = np.concatenate([ctrs - wh / 2, ctrs + wh / 2], axis=-1)
        return boxes.reshape(-1, 4).astype(np.float32)


class RPNHead(HybridBlock):
    """Shared conv3x3 + objectness/delta 1x1s applied to every level
    (GluonCV ``RPNHead``)."""

    def __init__(self, channels=256, num_anchors=3, **kwargs):
        super().__init__(**kwargs)
        self._na = num_anchors
        with self.name_scope():
            self.conv = nn.Conv2D(channels, 3, padding=1,
                                  in_channels=channels,
                                  activation="relu")
            self.obj = nn.Conv2D(num_anchors, 1, in_channels=channels)
            self.reg = nn.Conv2D(num_anchors * 4, 1, in_channels=channels)

    def hybrid_forward(self, F, x):
        t = self.conv(x)
        # (B, A, H, W) -> (B, H*W*A); (B, 4A, H, W) -> (B, H*W*A, 4)
        obj = F.transpose(self.obj(t), axes=(0, 2, 3, 1)) \
            .reshape((x.shape[0], -1))
        reg = F.transpose(self.reg(t), axes=(0, 2, 3, 1)) \
            .reshape((x.shape[0], -1, 4))
        return obj, reg


# ------------------------------------------------------------ box helpers
def box_iou(a, b):
    """IoU matrix: a (N,4), b (M,4) corner boxes -> (N,M) jnp array."""
    import jax.numpy as jnp
    a, b = a[:, None, :], b[None, :, :]
    lt = jnp.maximum(a[..., :2], b[..., :2])
    rb = jnp.minimum(a[..., 2:], b[..., 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[..., 2] - a[..., 0]) * (a[..., 3] - a[..., 1])
    area_b = (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])
    return inter / jnp.clip(area_a + area_b - inter, 1e-9)


def encode_deltas(anchors, gt):
    """Box regression targets (tx,ty,tw,th) — R-CNN parameterization.
    Degenerate (zero-area) anchors/rois are clamped so they encode to
    finite garbage rather than inf/nan — callers mask them out, and
    0 * inf would poison the loss otherwise."""
    import jax.numpy as jnp
    aw = jnp.clip(anchors[..., 2] - anchors[..., 0], 1e-6)
    ah = jnp.clip(anchors[..., 3] - anchors[..., 1], 1e-6)
    ax = anchors[..., 0] + aw / 2
    ay = anchors[..., 1] + ah / 2
    gw = jnp.clip(gt[..., 2] - gt[..., 0], 1e-6)
    gh = jnp.clip(gt[..., 3] - gt[..., 1], 1e-6)
    gx = gt[..., 0] + gw / 2
    gy = gt[..., 1] + gh / 2
    return jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                      jnp.log(gw / aw), jnp.log(gh / ah)], axis=-1)


def decode_deltas(anchors, deltas):
    """Inverse of encode_deltas -> corner boxes."""
    import jax.numpy as jnp
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    ax = anchors[..., 0] + aw / 2
    ay = anchors[..., 1] + ah / 2
    cx = deltas[..., 0] * aw + ax
    cy = deltas[..., 1] * ah + ay
    w = jnp.exp(jnp.clip(deltas[..., 2], -10, 10)) * aw
    h = jnp.exp(jnp.clip(deltas[..., 3], -10, 10)) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def nms_static(boxes, scores, topk, iou_thr=0.7):
    """Static-shape NMS: returns (boxes (topk,4), scores (topk,),
    keep-mask (topk,)).  Fixed ``topk`` iterations of greedy
    suppression over masked scores — the XLA-compilable equivalent of
    dynamic box_nms (suppressed slots keep score -inf)."""
    import jax
    import jax.numpy as jnp

    iou = box_iou(boxes, boxes)

    def body(live, _):
        masked = jnp.where(live, scores, -jnp.inf)
        i = jnp.argmax(masked)
        best_live = masked[i] > -jnp.inf
        # suppress everything overlapping the pick (including itself)
        live = live & ~(iou[i] > iou_thr) & \
            (jnp.arange(scores.shape[0]) != i)
        return live, (i, best_live)

    live0 = jnp.ones(scores.shape[0], bool)
    _, (idx, keep) = jax.lax.scan(body, live0, None, length=topk)
    return boxes[idx], jnp.where(keep, scores[idx], -jnp.inf), keep


def _match_gt(boxes, gt_boxes):
    """IoU-match fixed boxes against (possibly zero-area-padded) gt:
    -> (best_iou (N,), best_gt (N,)).  Shared by the RPN and ROI-head
    target assignment so the matching rule cannot drift between them."""
    import jax.numpy as jnp
    iou = box_iou(boxes, gt_boxes)
    valid_gt = (gt_boxes[:, 2] > gt_boxes[:, 0]) & \
        (gt_boxes[:, 3] > gt_boxes[:, 1])
    iou = jnp.where(valid_gt[None, :], iou, 0.0)
    return iou.max(axis=1), iou.argmax(axis=1)


def _smooth_l1(diff):
    """Huber/smooth-L1 summed over the last axis."""
    import jax.numpy as jnp
    return jnp.where(jnp.abs(diff) < 1.0, 0.5 * diff * diff,
                     jnp.abs(diff) - 0.5).sum(axis=-1)


def fpn_level_index(w, h, n_levels, base_level=3):
    """Canonical FPN ROI-to-level routing (k0=4, 224-canonical):
    ``k = floor(4 + log2(sqrt(wh)/224))`` is the ABSOLUTE pyramid
    level; subtract ``base_level`` (P3 = stride 2^3 is list index 0)
    before indexing the level list."""
    import jax.numpy as jnp
    k = jnp.floor(4 + jnp.log2(jnp.sqrt(jnp.clip(w * h, 1.0))
                               / 224.0 + 1e-6))
    return jnp.clip(k - base_level, 0, n_levels - 1).astype(jnp.int32)


class RCNNBoxHead(HybridBlock):
    """ROI feature -> (class scores, per-class deltas) (GluonCV
    ``FasterRCNN`` top: two FCs + parallel cls/reg)."""

    def __init__(self, num_classes, channels=256, roi_size=7,
                 hidden=1024, **kwargs):
        super().__init__(**kwargs)
        self._nc = num_classes
        in_units = channels * roi_size * roi_size
        with self.name_scope():
            self.fc1 = nn.Dense(hidden, activation="relu",
                                in_units=in_units)
            self.fc2 = nn.Dense(hidden, activation="relu",
                                in_units=hidden)
            self.cls = nn.Dense(num_classes + 1, in_units=hidden)
            self.reg = nn.Dense(num_classes * 4, in_units=hidden)

    def hybrid_forward(self, F, roi_feats):
        x = self.fc2(self.fc1(F.Flatten(roi_feats)))
        return self.cls(x), self.reg(x).reshape((-1, self._nc, 4))


class FasterRCNN(HybridBlock):
    """Minimal but complete two-stage detector over a caller-supplied
    multi-scale feature extractor.

    ``features(x) -> tuple of (B,C,H,W)`` stages (e.g. resnet C3-C5);
    this block adds FPN, RPN, static top-k proposal selection + NMS,
    level-assigned ROIAlign, and the box head.  ``rpn_targets`` /
    ``rpn_loss`` provide the first-stage training path (static-shape
    IoU matching — one compiled program every step).
    """

    def __init__(self, features, in_channels, num_classes,
                 image_size=(256, 256), channels=64, roi_size=7,
                 rpn_pre_topk=256, rpn_post_topk=64, ratios=(0.5, 1, 2),
                 **kwargs):
        super().__init__(**kwargs)
        self._nc = num_classes
        self._roi = roi_size
        self._pre = rpn_pre_topk
        self._post = rpn_post_topk
        n_levels = len(in_channels) + 1                 # + P6
        strides = tuple(2 ** (i + 3) for i in range(n_levels))
        sizes = tuple(2 ** (i + 5) for i in range(n_levels))
        self.anchors = AnchorGenerator(strides, sizes, ratios)
        self._image_size = image_size
        with self.name_scope():
            self.features = features
            self.fpn = FPN(in_channels, channels)
            self.rpn = RPNHead(channels, self.anchors.num_anchors)
            self.box_head = RCNNBoxHead(num_classes, channels, roi_size)

    # -------------------------------------------------------------- plumbing
    def _levels(self, x):
        feats = self.features(x)
        return self.fpn(*feats)

    def _flat_anchors(self, levels):
        anchors = [self.anchors.level(i, f.shape[2], f.shape[3])
                   for i, f in enumerate(levels)]
        return np.concatenate(anchors, axis=0)          # (N, 4)

    def rpn_forward(self, x):
        """-> (levels, anchors (N,4) np, obj (B,N), deltas (B,N,4))."""
        from ... import nd
        levels = self._levels(x)
        anchors = self._flat_anchors(levels)
        objs, regs = [], []
        for f in levels:
            o, r = self.rpn(f)
            objs.append(o)
            regs.append(r)
        obj = nd.concat(*objs, dim=1) if len(objs) > 1 else objs[0]
        reg = nd.concat(*regs, dim=1) if len(regs) > 1 else regs[0]
        return levels, anchors, obj, reg

    def proposals(self, anchors, obj, reg):
        """Static top-k + NMS per image -> (rois (B, post, 4),
        scores (B, post), keep (B, post)).  Slots past the NMS survivors
        hold DUPLICATES of the top box with score -inf and keep=False —
        consumers must respect the mask."""
        import jax
        import jax.numpy as jnp
        anchors_j = jnp.asarray(anchors)
        W, H = self._image_size[1], self._image_size[0]

        def one(o, r):
            score, idx = jax.lax.top_k(o, self._pre)
            boxes = decode_deltas(anchors_j[idx], r[idx])
            boxes = jnp.clip(boxes,
                             jnp.zeros(4, jnp.float32),
                             jnp.array([W, H, W, H], jnp.float32))
            return nms_static(boxes, score, self._post)

        return jax.vmap(one)(obj._data, reg._data)

    def roi_align(self, levels, rois):
        """FPN level assignment by box scale + ROIAlign (GluonCV
        ``_pyramid_roi_feats``): all levels aligned, one gathered.
        ``rois``: raw (B, R, 4) jnp array.  Dispatched as ONE op through
        the registry so the autograd tape links the output to the FPN
        feature maps — the second-stage gradient must reach the
        FPN/backbone, not stop at the align."""
        from ... import nd
        import jax.numpy as jnp
        from ...ops.registry import LightOpDef, invoke, get_op

        roi_fn = get_op("ROIAlign").fn
        strides = self.anchors.strides
        r = self._roi
        n_levels = len(levels)

        def fn(rois_j, *feats):
            B, R = rois_j.shape[0], rois_j.shape[1]
            w = rois_j[..., 2] - rois_j[..., 0]
            h = rois_j[..., 3] - rois_j[..., 1]
            lvl = fpn_level_index(w, h, n_levels)
            batch_ix = jnp.broadcast_to(
                jnp.arange(B, dtype=jnp.float32)[:, None], (B, R))
            flat = jnp.concatenate([batch_ix.reshape(-1, 1),
                                    rois_j.reshape(-1, 4)], axis=1)
            per_level = [
                roi_fn(f, flat, pooled_size=(r, r),
                       spatial_scale=1.0 / strides[i])
                for i, f in enumerate(feats)]
            stacked = jnp.stack(per_level, axis=0)   # (L, BR, C, r, r)
            return jnp.take_along_axis(
                stacked, lvl.reshape(1, -1, 1, 1, 1).astype(jnp.int32),
                axis=0)[0]

        op = LightOpDef("pyramid_roi_align", fn, 1 + n_levels, 1, True)
        return invoke(op, [nd.NDArray(jnp.asarray(rois)), *levels], {})

    def hybrid_forward(self, F, x):
        """Inference: -> (class scores (B,R,nc+1), boxes (B,R,nc,4),
        roi scores (B,R))."""
        from ... import nd
        levels, anchors, obj, reg = self.rpn_forward(x)
        rois, rscores, _keep = self.proposals(anchors, obj, reg)
        roi_feats = self.roi_align(levels, rois)
        cls, deltas = self.box_head(roi_feats)
        B, R = rois.shape[0], rois.shape[1]
        import jax.numpy as jnp
        boxes = decode_deltas(jnp.asarray(rois).reshape(B * R, 1, 4),
                              deltas._data)
        return (cls.reshape((B, R, -1)),
                nd.NDArray(boxes.reshape(B, R, self._nc, 4)),
                nd.NDArray(rscores))

    # -------------------------------------------------------------- training
    def rpn_targets(self, anchors, gt_boxes, pos_iou=0.5, neg_iou=0.3):
        """Per-image RPN targets: (obj_target (N,), obj_mask (N,),
        delta_target (N,4), pos_mask (N,)).  gt_boxes (G,4) jnp; G is
        static (pad with zero-area boxes)."""
        import jax.numpy as jnp
        anchors = jnp.asarray(anchors)
        best_iou, best_gt = _match_gt(anchors, gt_boxes)
        pos = best_iou >= pos_iou
        neg = best_iou < neg_iou
        obj_t = pos.astype(jnp.float32)
        obj_mask = (pos | neg).astype(jnp.float32)
        delta_t = encode_deltas(anchors, gt_boxes[best_gt])
        return obj_t, obj_mask, delta_t, pos.astype(jnp.float32)

    def rpn_loss(self, anchors, obj, reg, gt_boxes):
        """Batched RPN loss (objectness BCE + smooth-L1 on positives).
        Dispatched through the op registry so the autograd tape records
        it (a raw-jnp computation would be invisible to backward)."""
        import jax
        import jax.numpy as jnp

        from ...ops.registry import LightOpDef, invoke

        def one(o, r, gt):
            obj_t, obj_m, delta_t, pos = self.rpn_targets(anchors, gt)
            bce = jnp.maximum(o, 0) - o * obj_t + \
                jnp.log1p(jnp.exp(-jnp.abs(o)))
            cls_l = (bce * obj_m).sum() / jnp.clip(obj_m.sum(), 1.0)
            sl1 = _smooth_l1(r - delta_t)
            reg_l = (sl1 * pos).sum() / jnp.clip(pos.sum(), 1.0)
            return cls_l + reg_l

        def fn(o, r, g):
            return jax.vmap(one)(o, r, g).mean()

        op = LightOpDef("rpn_loss", fn, 3, 1, True)
        return invoke(op, [obj, reg, gt_boxes], {})

    def rcnn_targets(self, rois, gt_boxes, gt_classes, fg_iou=0.5):
        """Per-image second-stage targets over FIXED rois (R,4):
        (cls_target (R,) int — 0=background, 1..nc=fg;
         delta_target (R,4); fg_mask (R,)).  gt_classes are 1-based
        foreground ids; padded gt rows have zero area and never match."""
        import jax.numpy as jnp
        best_iou, best_gt = _match_gt(rois, gt_boxes)
        fg = best_iou >= fg_iou
        cls_t = jnp.where(fg, gt_classes[best_gt], 0).astype(jnp.int32)
        delta_t = encode_deltas(rois, gt_boxes[best_gt])
        return cls_t, delta_t, fg.astype(jnp.float32)

    def rcnn_loss(self, levels, rois, gt_boxes, gt_classes, keep=None):
        """Second-stage loss over the proposals: softmax CE over
        nc+1 classes + smooth-L1 on the matched class's deltas for
        foreground rois.  ``rois`` (B,R,4) raw jnp (treated as fixed
        samples — no gradient flows into the proposal coordinates,
        matching the two-stage training convention); ``keep`` (B,R) is
        the NMS validity mask from ``proposals`` — suppressed slots
        hold duplicates of the top box and must not be counted as
        extra training samples.  The head computation is dispatched
        through the op registry so the tape records it end to end
        (roi_align links back to the FPN features)."""
        import jax
        import jax.numpy as jnp

        from ...ops.registry import LightOpDef, invoke
        from ... import nd

        rois = jnp.asarray(rois)
        B, R = rois.shape[0], rois.shape[1]
        if keep is None:
            keep = jnp.ones((B, R), bool)
        roi_feats = self.roi_align(levels, rois)        # (BR, C, r, r)
        cls, deltas = self.box_head(roi_feats)          # (BR, nc+1), (BR, nc, 4)
        nc = self._nc

        def fn(cls_flat, deltas_flat, rois_b, gt_b, gtc_b, keep_b):
            def one(c, d, ro, gt, gtc, valid):
                valid = valid.astype(jnp.float32)
                cls_t, delta_t, fg = self.rcnn_targets(ro, gt, gtc)
                fg = fg * valid
                logp = jax.nn.log_softmax(c.astype(jnp.float32), -1)
                ce_all = -jnp.take_along_axis(
                    logp, cls_t[:, None], axis=1)[:, 0]
                ce = (ce_all * valid).sum() / jnp.clip(valid.sum(), 1.0)
                # pick the matched class's delta row (class 1 -> row 0)
                row = jnp.clip(cls_t - 1, 0)
                dsel = jnp.take_along_axis(
                    d, row[:, None, None].repeat(4, 2), axis=1)[:, 0]
                sl1 = _smooth_l1(dsel - delta_t)
                # where(), not multiply: a background roi's (unused)
                # delta target can be huge and 0 * inf = nan
                reg = jnp.where(fg > 0, sl1, 0.0).sum() / \
                    jnp.clip(fg.sum(), 1.0)
                return ce + reg

            return jax.vmap(one)(
                cls_flat.reshape(B, R, nc + 1),
                deltas_flat.reshape(B, R, nc, 4),
                rois_b, gt_b, gtc_b, keep_b).mean()

        op = LightOpDef("rcnn_loss", fn, 6, 1, True)
        return invoke(op, [cls, deltas, nd.NDArray(rois), gt_boxes,
                           gt_classes, nd.NDArray(jnp.asarray(keep))], {})
