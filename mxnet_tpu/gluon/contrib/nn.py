"""Contrib neural-network layers.

Reference surface: ``python/mxnet/gluon/contrib/nn/basic_layers.py`` —
``Concurrent``/``HybridConcurrent``, ``Identity``, ``SparseEmbedding``,
``SyncBatchNorm``, ``PixelShuffle2D``.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock
from ..nn import basic_layers as _nn

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm", "PixelShuffle2D"]


class HybridConcurrent(HybridBlock):
    """Run children on the same input and concat outputs along ``axis``
    (reference: contrib.nn.HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)

    def hybrid_forward(self, F, x):
        out = [child(x) for child in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Concurrent(HybridConcurrent):
    """Imperative alias (reference keeps both names)."""


class Identity(HybridBlock):
    """Pass-through block (reference: contrib.nn.Identity)."""

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(HybridBlock):
    """Embedding whose gradient is row-sparse (reference:
    contrib.nn.SparseEmbedding): only rows referenced this batch carry
    gradient, and sparse-aware optimizers (SGD/Adam lazy_update) touch
    only those rows."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, grad_stype="row_sparse")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=True)


class SyncBatchNorm(_nn.BatchNorm):
    """Cross-device batch normalization (reference:
    contrib.nn.SyncBatchNorm over NCCL allreduce of the statistics).

    TPU-native: under GSPMD (pjit / ShardedTrainer) the batch axis is a
    sharded mesh axis, so the batch-statistics reductions inside the
    compiled program are ALREADY global — XLA inserts the cross-replica
    collectives the reference performed by hand.  This subclass exists
    for API parity; ``num_devices`` is accepted and ignored.
    """

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class PixelShuffle2D(HybridBlock):
    """Depth-to-space upsampling (reference: contrib.nn.PixelShuffle2D):
    (N, C*f1*f2, H, W) -> (N, C, H*f1, W*f2)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        try:
            self._fh, self._fw = factor
        except TypeError:
            self._fh = self._fw = int(factor)

    def hybrid_forward(self, F, x):
        f1, f2 = self._fh, self._fw
        n, c, h, w = x.shape
        if c % (f1 * f2):
            raise MXNetError(
                f"PixelShuffle2D: channels {c} not divisible by "
                f"{f1}*{f2}")
        x = x.reshape((n, c // (f1 * f2), f1, f2, h, w))
        x = x.transpose((0, 1, 4, 2, 5, 3))
        return x.reshape((n, c // (f1 * f2), h * f1, w * f2))
