"""Contrib recurrent cells.

Reference surface: ``python/mxnet/gluon/contrib/rnn/`` —
``VariationalDropoutCell`` (one dropout mask per sequence, Gal & Ghahramani)
and ``Conv2DLSTMCell`` (convolutional state transitions, Shi et al.).
"""
from __future__ import annotations

from ...base import MXNetError
from ..rnn.rnn_cell import HybridRecurrentCell, ModifierCell

__all__ = ["VariationalDropoutCell", "Conv2DLSTMCell"]


class VariationalDropoutCell(ModifierCell):
    """Applies the SAME dropout mask at every time step (reference:
    contrib.rnn.VariationalDropoutCell).  Masks are drawn once per
    sequence (after reset()) from the framework RNG so they respect
    mx.random.seed.

    Imperative-only: the per-sequence mask is python-side state, which a
    hybridized trace would either leak (tracer escape) or silently
    re-randomize per step — calling this cell under hybridize raises
    instead (the reference cell has the same cached-mask design and the
    same limitation applies in spirit)."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self._drop_inputs = drop_inputs
        self._drop_states = drop_states
        self._drop_outputs = drop_outputs
        self._mask_in = None
        self._mask_states = None
        self._mask_out = None

    def reset(self):
        super().reset()
        self._mask_in = None
        self._mask_states = None
        self._mask_out = None

    @staticmethod
    def _mask(F, p, like):
        keep = F.random.uniform(0, 1, shape=like.shape) >= p
        return keep.astype(like.dtype) / (1 - p)

    def hybrid_forward(self, F, x, *states):
        import jax
        from ... import autograd
        if isinstance(getattr(x, "_data", None), jax.core.Tracer):
            raise MXNetError(
                "VariationalDropoutCell cannot be hybridized: the "
                "per-sequence dropout mask is python-side state that a "
                "compiled trace would re-randomize per step; use the "
                "cell imperatively")
        training = autograd.is_training()
        if training and self._drop_inputs:
            if self._mask_in is None:
                self._mask_in = self._mask(F, self._drop_inputs, x)
            x = x * self._mask_in
        if training and self._drop_states:
            if self._mask_states is None:
                self._mask_states = self._mask(F, self._drop_states,
                                               states[0])
            states = (states[0] * self._mask_states,) + tuple(states[1:])
        out, nstates = self.base_cell(x, list(states))
        if training and self._drop_outputs:
            if self._mask_out is None:
                self._mask_out = self._mask(F, self._drop_outputs, out)
            out = out * self._mask_out
        return out, nstates

    def _alias(self):
        return "vardrop"


class Conv2DLSTMCell(HybridRecurrentCell):
    """Convolutional LSTM over NCHW inputs (reference:
    contrib.rnn.Conv2DLSTMCell): gates computed by conv of input and
    hidden state; states are feature maps."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel,
                 h2h_kernel, i2h_pad=0, **kwargs):
        super().__init__(**kwargs)
        c_in, h, w = input_shape
        self._hidden_channels = hidden_channels
        k_i = i2h_kernel if isinstance(i2h_kernel, tuple) \
            else (i2h_kernel, i2h_kernel)
        k_h = h2h_kernel if isinstance(h2h_kernel, tuple) \
            else (h2h_kernel, h2h_kernel)
        if any(k % 2 == 0 for k in k_h):
            raise MXNetError("h2h_kernel must be odd (same-size state)")
        pad_i = i2h_pad if isinstance(i2h_pad, tuple) else (i2h_pad,
                                                            i2h_pad)
        # the state's spatial size is the i2h conv's OUTPUT size
        # (reference: _ConvRNNCell computes state_shape from the conv
        # arithmetic); the h2h conv is same-size over that
        state_h = h + 2 * pad_i[0] - k_i[0] + 1
        state_w = w + 2 * pad_i[1] - k_i[1] + 1
        if state_h < 1 or state_w < 1:
            raise MXNetError(
                f"Conv2DLSTMCell: i2h kernel {k_i} with pad {pad_i} "
                f"leaves no output for input {h}x{w}")
        self._state_shape = (hidden_channels, state_h, state_w)
        self._i2h_kernel, self._h2h_kernel = k_i, k_h
        self._i2h_pad = pad_i
        self._h2h_pad = (k_h[0] // 2, k_h[1] // 2)
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_channels, c_in) + k_i,
                allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(4 * hidden_channels, hidden_channels) + k_h,
                allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_channels,), init="zeros",
                allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_channels,), init="zeros",
                allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": "NCHW"},
                {"shape": (batch_size,) + self._state_shape,
                 "__layout__": "NCHW"}]

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F, x, h, c, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.Convolution(x, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=4 * self._hidden_channels)
        h2h = F.Convolution(h, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=4 * self._hidden_channels)
        gates = i2h + h2h
        slices = F.split(gates, num_outputs=4, axis=1)
        i = F.sigmoid(slices[0])
        f = F.sigmoid(slices[1])
        g = F.tanh(slices[2])
        o = F.sigmoid(slices[3])
        c_new = f * c + i * g
        h_new = o * F.tanh(c_new)
        return h_new, [h_new, c_new]
