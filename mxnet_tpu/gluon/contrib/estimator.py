"""Minimal Estimator: fit/evaluate convenience loop
(reference: python/mxnet/gluon/contrib/estimator/estimator.py)."""
from __future__ import annotations

from ... import autograd
from ...base import MXNetError
from ..trainer import Trainer

__all__ = ["Estimator"]


class Estimator:
    def __init__(self, net, loss, train_metrics=None, trainer=None,
                 context=None):
        self.net = net
        self.loss = loss
        self.train_metrics = train_metrics or []
        self.trainer = trainer
        self.context = context

    def fit(self, train_data, val_data=None, epochs=1):
        if self.trainer is None:
            raise MXNetError("Estimator needs a Trainer")
        history = []
        for epoch in range(epochs):
            for m in self.train_metrics:
                m.reset()
            n = 0
            for batch in train_data:
                data, label = batch[0], batch[1]
                with autograd.record():
                    out = self.net(data)
                    loss = self.loss(out, label)
                loss.backward()
                bs = data.shape[0]
                self.trainer.step(bs)
                n += bs
                for m in self.train_metrics:
                    m.update(label, out)
            history.append({m.name: m.get()[1]
                            for m in self.train_metrics})
        return history

    def evaluate(self, val_data, metrics=None):
        metrics = metrics or self.train_metrics
        for m in metrics:
            m.reset()
        for batch in val_data:
            data, label = batch[0], batch[1]
            out = self.net(data)
            for m in metrics:
                m.update(label, out)
        return {m.name: m.get()[1] for m in metrics}
