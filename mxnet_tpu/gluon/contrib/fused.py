"""FusedTrainStep: the whole training step as ONE compiled XLA program.

New TPU-first capability (no direct upstream equivalent — the closest
reference surface is the fused multi-tensor optimizer ops plus engine
bulk-exec, SURVEY.md §3.3/§7.3, which batch work but still dispatch
forward, backward and update separately).  The classic Gluon recipe

    with autograd.record():
        loss = block(*inputs)
    loss.backward()
    trainer.step(batch_size)

dispatches three XLA programs; gradients make a full HBM round trip
between backward and update, and each dispatch pays the (tunnel) launch
latency.  ``FusedTrainStep`` compiles forward+backward+optimizer into a
single donated program while the weights keep living in the Block's
``Parameter`` objects — ``save_parameters``, ``set_learning_rate``,
``export`` all keep working:

    step = FusedTrainStep(loss_block, trainer)
    for batch in loader:
        loss = step(*batch)                    # one XLA dispatch

Measured (BERT-large seq-128, one v5e chip): 0.35 -> ~0.45+ MFU vs the
three-call recipe, approaching the functional ``parallel.ShardedTrainer``
path.

Semantic differences from the three-call recipe (documented contract):
- parameter ``.grad`` buffers are NOT written (gradients exist only
  inside the compiled program); ``grad_req='add'`` accumulation is
  unsupported and raises.
- the autograd tape is bypassed — do not wrap calls in
  ``autograd.record()``.
- a step that fails AFTER dispatch consumes the donated weight and
  optimizer-state buffers (unlike the three-call recipe, which leaves
  weights intact).  Errors surfacing at dispatch poison the instance
  with a reload-and-``reset()`` message; with fully asynchronous
  dispatch an execution error can instead surface at a later sync point
  as a raw XLA error, and the next ``__call__`` detects the deleted
  buffers and raises the same guidance.
"""
from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp

from ...base import MXNetError, get_env
from ...ndarray import NDArray

__all__ = ["FusedTrainStep"]


from ..trainer import _state_raw as _as_raw           # noqa: E402
from ..trainer import _state_write_back as _write_back  # noqa: E402


class FusedTrainStep:
    """Compile ``block``'s loss forward + backward + ``trainer``'s
    optimizer into one donated XLA program (see module docstring).

    ``block`` must return the loss (any shape; it is summed for the
    backward seed, exactly like ``loss.backward()``'s default ones
    cotangent).  ``trainer`` must be single-context with a fused-capable
    optimizer and no kvstore.
    """

    def __init__(self, block, trainer):
        self._block = block
        self._trainer = trainer
        self._cache = {}
        self._poisoned = None
        o = trainer._optimizer
        if not getattr(o, "fused", False):
            raise MXNetError(
                f"FusedTrainStep: optimizer {type(o).__name__} has no "
                f"fused kernel")
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        if trainer._kvstore is not None or trainer._update_on_kvstore:
            raise MXNetError(
                "FusedTrainStep is single-context; use "
                "parallel.ShardedTrainer (or kvstore-backed Trainer.step) "
                "for multi-device training")
        for p in trainer._params:
            if p.grad_req == "add":
                raise MXNetError(
                    "FusedTrainStep cannot honor grad_req='add' "
                    "(gradients never materialize); use the "
                    "record/backward/step recipe for accumulation")
            if getattr(p, "_grad_stype", "default") != "default":
                raise MXNetError(
                    f"FusedTrainStep computes dense gradients; parameter "
                    f"{p.name!r} requests grad_stype="
                    f"{p._grad_stype!r} lazy sparse updates — use the "
                    f"record/backward/step recipe")

    def reset(self):
        """Clear the poisoned flag after parameters (and optimizer state)
        have been reloaded following a failed donated step.

        Optimizer states the user restored (``trainer.load_states``) are
        kept; only states still pointing at buffers deleted by the failed
        donation are dropped (they are recreated from scratch on the next
        step)."""
        self._poisoned = None
        upd = self._trainer._updater
        for i in list(upd.states):
            leaves = jax.tree_util.tree_leaves(_as_raw(upd.states[i]))
            if any(getattr(a, "is_deleted", lambda: False)()
                   for a in leaves):
                del upd.states[i]
        for entry in self._cache.values():
            entry["ts"] = None      # ts was donated with weights/states

    # ---------------------------------------------------------------- build
    def _build(self, sig, inputs):
        from ...gluon.block import _AUX_CAPTURE, _TRACING, _flatten
        from ...gluon.parameter import _PARAM_OVERRIDE
        from ... import autograd, random as mxrand

        trainer = self._trainer
        o = trainer._optimizer
        block = self._block

        params = OrderedDict(block.collect_params().items())
        trainable, frozen = [], []
        t_index = {id(p): i for i, p in enumerate(trainer._params)}
        for name, p in params.items():
            if p.grad_req == "null":
                frozen.append((name, p))
            elif id(p) in t_index:
                trainable.append((t_index[id(p)], name, p))
            else:
                # a second Trainer managing this param would read .grad
                # buffers this step never writes: refuse loudly
                raise MXNetError(
                    f"FusedTrainStep: parameter {name!r} has "
                    f"grad_req={p.grad_req!r} but is not managed by the "
                    f"given trainer; multi-trainer setups need the "
                    f"record/backward/step recipe (or grad_req='null' "
                    f"to freeze it)")
        if not trainable:
            raise MXNetError("FusedTrainStep: no trainable parameters")

        n_in = len(inputs)
        t_names = [n for _i, n, _p in trainable]
        f_names = [n for n, _p in frozen]
        aux_order = []                      # Parameter objs, fixed at trace

        def forward(key, input_arrays, weight_arrays, frozen_arrays):
            xs = [NDArray(a) for a in input_arrays]
            override = {params[n]: NDArray(a)
                        for n, a in zip(t_names, weight_arrays)}
            override.update({params[n]: NDArray(a)
                             for n, a in zip(f_names, frozen_arrays)})
            tok_t = _TRACING.set(True)
            tok_p = _PARAM_OVERRIDE.set(override)
            tok_a = _AUX_CAPTURE.set(OrderedDict())
            try:
                with mxrand.trace_key_scope(key):
                    with autograd.pause(train_mode=True):
                        out = block.forward(*xs)
                cap = _AUX_CAPTURE.get()
            finally:
                _AUX_CAPTURE.reset(tok_a)
                _PARAM_OVERRIDE.reset(tok_p)
                _TRACING.reset(tok_t)
            flat, _tree = _flatten(out)
            if not aux_order:
                aux_order.extend(cap.keys())
            return flat[0]._data, tuple(cap.values())

        policy_name = get_env("MXNET_CACHED_OP_SAVE_POLICY")
        policies = {
            "all": None,
            "dots": jax.checkpoint_policies.dots_saveable,
            "dots_no_batch":
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            "none": jax.checkpoint_policies.nothing_saveable,
        }
        policy = policies.get(str(policy_name), policies["dots_no_batch"])

        def prog(key, ts, lrs, wds, rescale, input_arrays, weights,
                 frozen_arrays, states):
            def loss_fn(ws):
                loss, aux = forward(key, input_arrays, ws, frozen_arrays)
                return loss.astype(jnp.float32).sum(), (loss, aux)

            fn = loss_fn if policy is None else \
                jax.checkpoint(loss_fn, policy=policy)
            (_total, (loss, aux)), grads = \
                jax.value_and_grad(fn, has_aux=True)(list(weights))
            new_w, new_s = [], []
            for k, (w, g, s) in enumerate(zip(weights, grads, states)):
                nw, ns = o._fused_one(w, g, s, ts[k], lrs[k], wds[k],
                                      rescale)
                new_w.append(nw)
                new_s.append(ns)
            return loss, aux, new_w, new_s, ts + 1.0

        # weights, states and ts are donated: in-place update at the
        # memory level (the static-alloc contract)
        jitted = jax.jit(prog, donate_argnums=(1, 6, 8))
        entry = {"prog": jitted, "trainable": trainable, "frozen": frozen,
                 "aux_order": aux_order, "ts": None, "counts": None,
                 "hyper": None}
        self._cache[sig] = entry
        return entry

    # ----------------------------------------------------------------- call
    def __call__(self, *inputs, batch_size=None):
        from ... import random as mxrand
        from ...gluon.block import update_aux_state

        from ... import autograd

        if self._poisoned is not None:
            raise MXNetError(
                "FusedTrainStep: a previous donated step failed after "
                "dispatch; the block's weight and optimizer-state buffers "
                "were consumed and are gone.  Reload parameters "
                "(load_parameters / initialize(force_reinit=True)), then "
                "call .reset() on this FusedTrainStep (or construct a new "
                "one) before training again.  Original failure: "
                f"{self._poisoned!r}") from self._poisoned

        trainer = self._trainer
        o = trainer._optimizer
        upd = trainer._updater
        if batch_size is None:
            batch_size = inputs[0].shape[0]
        o.rescale_grad = trainer._scale / batch_size

        ctx = inputs[0].context
        block_params = self._block.collect_params()
        if any(p._deferred_init is not None or not p._data
               for p in block_params.values()):
            # one predict-mode pass resolves deferred shapes (same
            # mechanism as parallel.functionalize)
            with autograd.pause(train_mode=False):
                self._block(*inputs)
        sig = (tuple((tuple(x.shape), str(x._data.dtype)) for x in inputs),
               tuple((n, tuple(p.shape), str(p.dtype))
                     for n, p in block_params.items()),
               type(o), o._fused_key())
        entry = self._cache.get(sig)
        if entry is None:
            entry = self._build(sig, inputs)
        trainable, frozen = entry["trainable"], entry["frozen"]

        # detect an asynchronously-surfaced donation failure BEFORE the
        # bookkeeping below advances update counts (a failed/aborted step
        # must never advance schedules)
        stale = [a for _i, _n, p in trainable
                 for a in (p.data(ctx)._data,)] + [
            a for i, _n, _p in trainable if i in upd.states
            for a in jax.tree_util.tree_leaves(_as_raw(upd.states[i]))]
        if any(getattr(a, "is_deleted", lambda: False)() for a in stale):
            raise MXNetError(
                "FusedTrainStep: weight/optimizer-state buffers were "
                "deleted by a previously failed donated step (the failure "
                "surfaced asynchronously).  Reload parameters, then call "
                ".reset() (or construct a new FusedTrainStep).")

        # same per-step bookkeeping as Trainer._fused_update: ensure
        # states, advance the python-side update counts, keep ts on device
        prev_num_update = o.num_update
        for i, _n, p in trainable:
            if i not in upd.states:
                upd.states[i] = o.create_state_multi_precision(i, p.data())
            o._update_count(i)
        counts = [o._index_update_count[i] for i, _n, _p in trainable]
        if entry["ts"] is None or entry["counts"] != counts:
            entry["ts"] = jnp.asarray([float(c) for c in counts],
                                      jnp.float32)
        entry["counts"] = [c + 1 for c in counts]
        lrs_py = tuple(float(o._get_lr(i)) for i, _n, _p in trainable)
        wds_py = tuple(float(o._get_wd(i)) for i, _n, _p in trainable)
        rs_py = float(o.rescale_grad)
        if entry["hyper"] != (lrs_py, wds_py, rs_py):
            entry["lrs"] = jnp.asarray(lrs_py, jnp.float32)
            entry["wds"] = jnp.asarray(wds_py, jnp.float32)
            entry["rescale"] = jnp.float32(rs_py)
            entry["hyper"] = (lrs_py, wds_py, rs_py)

        weights = [p.data(ctx)._data for _i, _n, p in trainable]
        frozen_arrays = [p.data(ctx)._data for _n, p in frozen]
        states = [_as_raw(upd.states[i]) for i, _n, _p in trainable]
        key = mxrand.next_key()

        try:
            loss, aux, new_w, new_s, new_ts = entry["prog"](
                key, entry["ts"], entry["lrs"], entry["wds"],
                entry["rescale"], [x._data for x in inputs], weights,
                frozen_arrays, states)
        except BaseException as e:
            # the program donated weights/states: a failure after dispatch
            # (async XLA error, OOM, interrupt — incl. KeyboardInterrupt,
            # hence BaseException) consumes them without the write-back
            # below ever running — unlike the three-call recipe a failed
            # fused step does NOT leave weights intact.  Trace/compile
            # failures happen BEFORE donation though, so only poison when
            # a donated buffer was actually deleted.
            consumed = any(
                getattr(a, "is_deleted", lambda: False)()
                for a in jax.tree_util.tree_leaves((weights, states)))
            # the failed step never applied: roll back the update counts
            # advanced above so lr schedules / bias correction don't drift
            # (num_update advanced via max(); restore it alongside)
            for i, _n, _p in trainable:
                o._index_update_count[i] -= 1
            o.num_update = prev_num_update
            entry["counts"] = counts
            if not consumed:
                raise
            self._poisoned = e
            entry["ts"] = None          # donated alongside weights/states
            if isinstance(e, Exception):
                raise MXNetError(
                    "FusedTrainStep failed after dispatch; weight and "
                    "optimizer-state buffers were donated to the failed "
                    "program and may be deleted.  Reload parameters, then "
                    "call .reset() (or construct a new FusedTrainStep). "
                    f"Cause: {e!r}") from e
            raise   # KeyboardInterrupt/SystemExit must propagate as-is
        entry["ts"] = new_ts
        for (i, _n, p), nw, ns in zip(trainable, new_w, new_s):
            p.data(ctx)._set_data(nw)
            _write_back(upd.states[i], ns)
        for p, v in zip(entry["aux_order"], aux):
            update_aux_state(p, v, ctx=None)
        out = NDArray(loss)
        from ...engine import engine, is_naive
        if is_naive():
            out.wait_to_read()
        engine().track(out)
        return out
