"""Mixture-of-Experts Gluon layer (expert-parallel on the ``ep`` mesh
axis).

New TPU-first capability — upstream MXNet has no MoE (SURVEY.md §2.4:
EP absent; flagged as new capability).  Wraps ``ops/moe.py``'s
GShard-style dense-routing op: parameters are named so
``parallel.MEGATRON_RULES`` shards the expert dim over ``ep`` (the
dispatch/combine einsums then lower to ICI all-to-alls under GSPMD).

    layer = MoEFFN(units=512, hidden_size=2048, num_experts=8)
    out, aux_loss = layer(x)          # add aux_weight*aux_loss to loss
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["MoEFFN"]


class MoEFFN(HybridBlock):
    """Switch/GShard top-1 MoE feed-forward block.

    Inputs (..., units); returns (output (..., units), aux_loss ()).
    Tokens routed past an expert's ``capacity_factor`` allowance are
    dropped (carried by the caller's residual connection, per GShard).
    """

    def __init__(self, units, hidden_size, num_experts,
                 capacity_factor=1.25, activation="gelu",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        if num_experts < 1:
            raise MXNetError("MoEFFN needs num_experts >= 1")
        if activation not in ("relu", "gelu"):
            raise MXNetError(
                f"MoEFFN: unsupported activation {activation!r} "
                f"(supported: 'relu', 'gelu')")
        self._capacity_factor = float(capacity_factor)
        self._activation = activation
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(units, num_experts),
                init=weight_initializer)
            self.expert_w1 = self.params.get(
                "expert_w1", shape=(num_experts, units, hidden_size),
                init=weight_initializer)
            self.expert_b1 = self.params.get(
                "expert_b1", shape=(num_experts, hidden_size), init="zeros")
            self.expert_w2 = self.params.get(
                "expert_w2", shape=(num_experts, hidden_size, units),
                init=weight_initializer)
            self.expert_b2 = self.params.get(
                "expert_b2", shape=(num_experts, units), init="zeros")

    def hybrid_forward(self, F, x, gate_weight, expert_w1, expert_b1,
                       expert_w2, expert_b2):
        out, aux = F.moe_ffn(x, gate_weight, expert_w1, expert_b1,
                             expert_w2, expert_b2,
                             capacity_factor=self._capacity_factor,
                             activation=self._activation)
        return out, aux
