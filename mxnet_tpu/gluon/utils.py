"""Gluon utilities (reference: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import hashlib

from ..base import MXNetError
from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch axis into num_slice chunks
    (reference: utils.split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"cannot evenly split batch of {size} into {num_slice} slices "
            f"(set even_split=False to allow uneven)")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(axis=batch_axis, begin=begin, end=end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split batch and place one slice per context
    (reference: utils.split_and_load — the P1 data-parallel primitive)."""
    if not isinstance(data, NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(c) for s, c in zip(slices, ctx_list)]


def _clip_global_norm_impl(datas, max_norm):
    import jax.numpy as jnp
    total = jnp.sqrt(sum(jnp.sum(jnp.square(d.astype(jnp.float32)))
                         for d in datas))
    # rescale only a finite, over-threshold norm: a nan/inf norm must leave
    # the arrays untouched (multiplying by nan would poison every gradient;
    # the reference's `scale < 1.0` guard is likewise nan-false)
    scale = jnp.where(jnp.isfinite(total) & (total > max_norm),
                      max_norm / (total + 1e-8), 1.0)
    return [(d * scale.astype(d.dtype)) for d in datas], total


_clip_global_norm_jit = None


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so the global L2 norm <= max_norm
    (reference: utils.clip_global_norm).

    One fused XLA program — norm, scale, and rescale all on device.  With
    ``check_isfinite`` there is exactly one host sync (to inspect the norm)
    and the float norm is returned; without it the call is fully async and
    the norm comes back as a lazy NDArray, like the reference.
    """
    import jax
    global _clip_global_norm_jit
    if not arrays:
        raise MXNetError("clip_global_norm: empty array list")
    if _clip_global_norm_jit is None:
        # max_norm is a TRACED scalar, not a static arg: a clipping
        # schedule that varies the threshold per step must reuse ONE
        # compiled program, not compile one per distinct value
        # (recompile-churn: each static value is a new XLA program)
        _clip_global_norm_jit = jax.jit(_clip_global_norm_impl)
    scaled, total = _clip_global_norm_jit([a._data for a in arrays],
                                          float(max_norm))
    for a, s in zip(arrays, scaled):
        a._set_data(s)
    if check_isfinite:
        t = float(jax.device_get(total))
        if not (t < float("inf")):
            import warnings
            warnings.warn("nan or inf found in gradients during "
                          "clip_global_norm")
        return t
    return NDArray(total)


def check_sha1(filename, sha1_hash):
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Reference: utils.download.  This build runs without network egress;
    the function exists for API parity and raises a clear error."""
    raise MXNetError(
        "download() is unavailable: this environment has no network "
        "access. Place files locally and pass the path instead.")
