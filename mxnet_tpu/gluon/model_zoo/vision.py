"""Vision model zoo (reference: python/mxnet/gluon/model_zoo/vision/).

ResNet V1/V2 (basic + bottleneck), VGG, AlexNet, MobileNet V1/V2,
SqueezeNet — built from gluon.nn layers; NCHW layout (channels-first maps
onto XLA's preferred conv layouts on TPU after the compiler's layout pass).
Pretrained-weight download is unavailable (no egress); ``pretrained=True``
raises with instructions to load local .params via load_parameters.
"""
from __future__ import annotations

from ...base import MXNetError
from .. import nn
from ..block import HybridBlock

__all__ = ["get_model", "ResNetV1", "ResNetV2", "VGG", "AlexNet",
           "MobileNet", "MobileNetV2", "SqueezeNet", "DenseNet",
           "Inception3",
           "resnet18_v1", "resnet34_v1", "resnet50_v1", "resnet101_v1",
           "resnet152_v1", "resnet18_v2", "resnet34_v2", "resnet50_v2",
           "resnet101_v2", "resnet152_v2", "vgg11", "vgg13", "vgg16",
           "vgg19", "alexnet", "mobilenet1_0", "mobilenet0_5",
           "mobilenet_v2_1_0", "squeezenet1_0", "densenet121",
           "densenet161", "densenet169", "densenet201", "inception_v3"]


# ---------------------------------------------------------------- ResNet V1
class BasicBlockV1(HybridBlock):
    """ResNet V1 basic block (reference: model_zoo/vision/resnet.py)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels, 3, stride, 1,
                                in_channels=in_channels, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 3, 1, 1, in_channels=channels,
                                use_bias=False))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, 1, stride,
                                          in_channels=in_channels,
                                          use_bias=False))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BottleneckV1(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.Conv2D(channels // 4, 1, stride,
                                in_channels=in_channels, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels // 4, 3, 1, 1,
                                in_channels=channels // 4, use_bias=False))
        self.body.add(nn.BatchNorm())
        self.body.add(nn.Activation("relu"))
        self.body.add(nn.Conv2D(channels, 1, 1, in_channels=channels // 4,
                                use_bias=False))
        self.body.add(nn.BatchNorm())
        if downsample:
            self.downsample = nn.HybridSequential(prefix="")
            self.downsample.add(nn.Conv2D(channels, 1, stride,
                                          in_channels=in_channels,
                                          use_bias=False))
            self.downsample.add(nn.BatchNorm())
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.body(x)
        if self.downsample is not None:
            residual = self.downsample(residual)
        return F.Activation(x + residual, act_type="relu")


class BasicBlockV2(HybridBlock):
    """Pre-activation block (reference: resnet.py BasicBlockV2)."""

    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels, 3, stride, 1,
                               in_channels=in_channels, use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = nn.Conv2D(channels, 3, 1, 1, in_channels=channels,
                               use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        in_channels=in_channels,
                                        use_bias=False)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        return x + residual


class BottleneckV2(HybridBlock):
    def __init__(self, channels, stride, downsample=False, in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self.bn1 = nn.BatchNorm()
        self.conv1 = nn.Conv2D(channels // 4, 1, 1, use_bias=False)
        self.bn2 = nn.BatchNorm()
        self.conv2 = nn.Conv2D(channels // 4, 3, stride, 1, use_bias=False)
        self.bn3 = nn.BatchNorm()
        self.conv3 = nn.Conv2D(channels, 1, 1, use_bias=False)
        if downsample:
            self.downsample = nn.Conv2D(channels, 1, stride,
                                        use_bias=False)
        else:
            self.downsample = None

    def hybrid_forward(self, F, x):
        residual = x
        x = self.bn1(x)
        x = F.Activation(x, act_type="relu")
        if self.downsample is not None:
            residual = self.downsample(x)
        x = self.conv1(x)
        x = self.bn2(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv2(x)
        x = self.bn3(x)
        x = F.Activation(x, act_type="relu")
        x = self.conv3(x)
        return x + residual


class ResNetV1(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            if thumbnail:
                self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                            use_bias=False))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=channels[i]))
            self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.Dense(classes, in_units=channels[-1])

    def _make_layer(self, block, layers, channels, stride, stage_index,
                    in_channels=0):
        layer = nn.HybridSequential(prefix=f"stage{stage_index}_")
        with layer.name_scope():
            layer.add(block(channels, stride, channels != in_channels,
                            in_channels=in_channels, prefix=""))
            for _ in range(layers - 1):
                layer.add(block(channels, 1, False, in_channels=channels,
                                prefix=""))
        return layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(F.flatten(x))


class ResNetV2(HybridBlock):
    def __init__(self, block, layers, channels, classes=1000,
                 thumbnail=False, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.BatchNorm(scale=False, center=False))
            if thumbnail:
                self.features.add(nn.Conv2D(channels[0], 3, 1, 1,
                                            use_bias=False))
            else:
                self.features.add(nn.Conv2D(channels[0], 7, 2, 3,
                                            use_bias=False))
                self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(3, 2, 1))
            in_channels = channels[0]
            for i, num_layer in enumerate(layers):
                stride = 1 if i == 0 else 2
                self.features.add(self._make_layer(
                    block, num_layer, channels[i + 1], stride, i + 1,
                    in_channels=in_channels))
                in_channels = channels[i + 1]
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes, in_units=in_channels)

    _make_layer = ResNetV1._make_layer

    def hybrid_forward(self, F, x):
        x = self.features(x)
        return self.output(x)


_RESNET_SPEC = {
    18: ("basic_block", [2, 2, 2, 2], [64, 64, 128, 256, 512]),
    34: ("basic_block", [3, 4, 6, 3], [64, 64, 128, 256, 512]),
    50: ("bottle_neck", [3, 4, 6, 3], [64, 256, 512, 1024, 2048]),
    101: ("bottle_neck", [3, 4, 23, 3], [64, 256, 512, 1024, 2048]),
    152: ("bottle_neck", [3, 8, 36, 3], [64, 256, 512, 1024, 2048]),
}
_RESNET_NET = {1: ResNetV1, 2: ResNetV2}
_RESNET_BLOCK = {1: {"basic_block": BasicBlockV1,
                     "bottle_neck": BottleneckV1},
                 2: {"basic_block": BasicBlockV2,
                     "bottle_neck": BottleneckV2}}


def get_resnet(version, num_layers, pretrained=False, classes=1000,
               **kwargs):
    if pretrained:
        raise MXNetError(
            "pretrained weights unavailable (no network egress); load a "
            "local .params file with net.load_parameters instead")
    block_type, layers, channels = _RESNET_SPEC[num_layers]
    net_cls = _RESNET_NET[version]
    block_cls = _RESNET_BLOCK[version][block_type]
    return net_cls(block_cls, layers, channels, classes=classes, **kwargs)


def resnet18_v1(**kw):
    return get_resnet(1, 18, **kw)


def resnet34_v1(**kw):
    return get_resnet(1, 34, **kw)


def resnet50_v1(**kw):
    return get_resnet(1, 50, **kw)


def resnet101_v1(**kw):
    return get_resnet(1, 101, **kw)


def resnet152_v1(**kw):
    return get_resnet(1, 152, **kw)


def resnet18_v2(**kw):
    return get_resnet(2, 18, **kw)


def resnet34_v2(**kw):
    return get_resnet(2, 34, **kw)


def resnet50_v2(**kw):
    return get_resnet(2, 50, **kw)


def resnet101_v2(**kw):
    return get_resnet(2, 101, **kw)


def resnet152_v2(**kw):
    return get_resnet(2, 152, **kw)


# -------------------------------------------------------------------- VGG
class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            for i, num in enumerate(layers):
                for _ in range(num):
                    self.features.add(nn.Conv2D(filters[i], 3, 1, 1))
                    if batch_norm:
                        self.features.add(nn.BatchNorm())
                    self.features.add(nn.Activation("relu"))
                self.features.add(nn.MaxPool2D(2, 2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


_VGG_SPEC = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
             13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
             16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
             19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


def get_vgg(num_layers, pretrained=False, **kwargs):
    if pretrained:
        raise MXNetError("pretrained weights unavailable (no egress)")
    layers, filters = _VGG_SPEC[num_layers]
    return VGG(layers, filters, **kwargs)


def vgg11(**kw):
    return get_vgg(11, **kw)


def vgg13(**kw):
    return get_vgg(13, **kw)


def vgg16(**kw):
    return get_vgg(16, **kw)


def vgg19(**kw):
    return get_vgg(19, **kw)


# ----------------------------------------------------------------- AlexNet
class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(64, 11, 4, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Conv2D(192, 5, padding=2,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Conv2D(384, 3, padding=1,
                                        activation="relu"))
            self.features.add(nn.Conv2D(256, 3, padding=1,
                                        activation="relu"))
            self.features.add(nn.Conv2D(256, 3, padding=1,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(nn.Flatten())
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.features.add(nn.Dense(4096, activation="relu"))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def alexnet(pretrained=False, **kw):
    if pretrained:
        raise MXNetError("pretrained weights unavailable (no egress)")
    return AlexNet(**kw)


# --------------------------------------------------------------- MobileNet
def _add_conv(out, channels=1, kernel=1, stride=1, pad=0, num_group=1,
              active=True, relu6=False):
    out.add(nn.Conv2D(channels, kernel, stride, pad, groups=num_group,
                      use_bias=False))
    out.add(nn.BatchNorm())
    if active:
        out.add(nn.Lambda(lambda x: x.clip(0, 6)) if relu6
                else nn.Activation("relu"))


class MobileNet(HybridBlock):
    """MobileNet V1 (reference: model_zoo/vision/mobilenet.py)."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        dw_channels = [int(x * multiplier) for x in
                       [32, 64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024]]
        channels = [int(x * multiplier) for x in
                    [64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024] * 2]
        strides = [1, 2] * 3 + [1] * 5 + [2, 1]
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            _add_conv(self.features, int(32 * multiplier), 3, 2, 1)
            for dwc, c, s in zip(dw_channels, channels, strides):
                _add_conv(self.features, dwc, 3, s, 1, num_group=dwc)
                _add_conv(self.features, c)
            self.features.add(nn.GlobalAvgPool2D())
            self.features.add(nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class LinearBottleneck(HybridBlock):
    def __init__(self, in_channels, channels, t, stride, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_channels == channels
        with self.name_scope():
            self.out = nn.HybridSequential()
            _add_conv(self.out, in_channels * t, relu6=True)
            _add_conv(self.out, in_channels * t, 3, stride, 1,
                      num_group=in_channels * t, relu6=True)
            _add_conv(self.out, channels, active=False)

    def hybrid_forward(self, F, x):
        out = self.out(x)
        if self.use_shortcut:
            out = out + x
        return out


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="features_")
            with self.features.name_scope():
                _add_conv(self.features, int(32 * multiplier), 3, 2, 1,
                          relu6=True)
                in_ch = [int(multiplier * x) for x in
                         [32] + [16] + [24] * 2 + [32] * 3 + [64] * 4
                         + [96] * 3 + [160] * 3]
                ch = [int(multiplier * x) for x in
                      [16] + [24] * 2 + [32] * 3 + [64] * 4 + [96] * 3
                      + [160] * 3 + [320]]
                ts = [1] + [6] * 16
                strides = [1, 2] * 2 + [1, 1, 2] + [1] * 6 + [2] + [1] * 3
                for i, c, t, s in zip(in_ch, ch, ts, strides):
                    self.features.add(LinearBottleneck(i, c, t, s))
                last = int(1280 * multiplier) if multiplier > 1.0 else 1280
                _add_conv(self.features, last, relu6=True)
                self.features.add(nn.GlobalAvgPool2D())
            self.output = nn.HybridSequential(prefix="output_")
            with self.output.name_scope():
                self.output.add(nn.Conv2D(classes, 1, use_bias=False,
                                          prefix="pred_"))
                self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def mobilenet1_0(**kw):
    return MobileNet(1.0, **kw)


def mobilenet0_5(**kw):
    return MobileNet(0.5, **kw)


def mobilenet_v2_1_0(**kw):
    return MobileNetV2(1.0, **kw)


# -------------------------------------------------------------- SqueezeNet
class _FireBlock(HybridBlock):
    def __init__(self, squeeze, expand1x1, expand3x3, **kwargs):
        super().__init__(**kwargs)
        self.squeeze = nn.Conv2D(squeeze, 1, activation="relu")
        self.expand1 = nn.Conv2D(expand1x1, 1, activation="relu")
        self.expand3 = nn.Conv2D(expand3x3, 3, padding=1, activation="relu")

    def hybrid_forward(self, F, x):
        x = self.squeeze(x)
        return F.concat(self.expand1(x), self.expand3(x), dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version="1.0", classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(nn.Conv2D(96, 7, 2, activation="relu"))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            for sq, e1, e3 in [(16, 64, 64), (16, 64, 64), (32, 128, 128)]:
                self.features.add(_FireBlock(sq, e1, e3))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            for sq, e1, e3 in [(32, 128, 128), (48, 192, 192),
                               (48, 192, 192), (64, 256, 256)]:
                self.features.add(_FireBlock(sq, e1, e3))
            self.features.add(nn.MaxPool2D(3, 2, ceil_mode=True))
            self.features.add(_FireBlock(64, 256, 256))
            self.features.add(nn.Dropout(0.5))
            self.output = nn.HybridSequential(prefix="")
            self.output.add(nn.Conv2D(classes, 1, activation="relu"))
            self.output.add(nn.GlobalAvgPool2D())
            self.output.add(nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def squeezenet1_0(**kw):
    return SqueezeNet("1.0", **kw)


# ---------------------------------------------------------------- DenseNet
class _DenseLayer(HybridBlock):
    """BN→ReLU→1x1→BN→ReLU→3x3, output concatenated onto the input
    (reference: model_zoo/vision/densenet.py _make_dense_layer)."""

    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential(prefix="")
        self.body.add(nn.BatchNorm(), nn.Activation("relu"),
                      nn.Conv2D(bn_size * growth_rate, 1, use_bias=False),
                      nn.BatchNorm(), nn.Activation("relu"),
                      nn.Conv2D(growth_rate, 3, padding=1, use_bias=False))
        self.dropout = nn.Dropout(dropout) if dropout else None

    def hybrid_forward(self, F, x):
        out = self.body(x)
        if self.dropout is not None:
            out = self.dropout(out)
        return F.concat(x, out, dim=1)


def _transition(channels):
    out = nn.HybridSequential(prefix="")
    out.add(nn.BatchNorm(), nn.Activation("relu"),
            nn.Conv2D(channels, 1, use_bias=False), nn.AvgPool2D(2, 2))
    return out


_DENSENET_SPEC = {121: (64, 32, [6, 12, 24, 16]),
                  161: (96, 48, [6, 12, 36, 24]),
                  169: (64, 32, [6, 12, 32, 32]),
                  201: (64, 32, [6, 12, 48, 32])}


class DenseNet(HybridBlock):
    """DenseNet-BC (reference: model_zoo/vision/densenet.py)."""

    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(
                nn.Conv2D(num_init_features, 7, 2, 3, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                nn.MaxPool2D(3, 2, 1))
            channels = num_init_features
            for i, n_layers in enumerate(block_config):
                for _ in range(n_layers):
                    self.features.add(_DenseLayer(growth_rate, bn_size,
                                                  dropout))
                    channels += growth_rate
                if i != len(block_config) - 1:
                    channels //= 2
                    self.features.add(_transition(channels))
            self.features.add(nn.BatchNorm(), nn.Activation("relu"),
                              nn.GlobalAvgPool2D(), nn.Flatten())
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def _densenet(num_layers, **kw):
    if kw.pop("pretrained", False):
        raise MXNetError("pretrained weights unavailable (no egress)")
    init_f, growth, cfg = _DENSENET_SPEC[num_layers]
    return DenseNet(init_f, growth, cfg, **kw)


def densenet121(**kw):
    return _densenet(121, **kw)


def densenet161(**kw):
    return _densenet(161, **kw)


def densenet169(**kw):
    return _densenet(169, **kw)


def densenet201(**kw):
    return _densenet(201, **kw)


# ------------------------------------------------------------ Inception V3
def _inc_conv(channels, kernel, stride=1, padding=0):
    out = nn.HybridSequential(prefix="")
    out.add(nn.Conv2D(channels, kernel, stride, padding, use_bias=False),
            nn.BatchNorm(epsilon=0.001), nn.Activation("relu"))
    return out


def _IncBranches(branches):
    """Parallel branches concatenated on channels (the reference
    inception.py builds exactly this from contrib HybridConcurrent)."""
    from ..contrib.nn import HybridConcurrent
    out = HybridConcurrent(axis=1)
    out.add(*branches)
    return out


def _seq(*blocks):
    out = nn.HybridSequential(prefix="")
    out.add(*blocks)
    return out


def _inc_a(pool_features):
    return _IncBranches([
        _inc_conv(64, 1),
        _seq(_inc_conv(48, 1), _inc_conv(64, 5, padding=2)),
        _seq(_inc_conv(64, 1), _inc_conv(96, 3, padding=1),
             _inc_conv(96, 3, padding=1)),
        _seq(nn.AvgPool2D(3, 1, 1), _inc_conv(pool_features, 1))])


def _inc_b():
    return _IncBranches([
        _inc_conv(384, 3, 2),
        _seq(_inc_conv(64, 1), _inc_conv(96, 3, padding=1),
             _inc_conv(96, 3, 2)),
        nn.MaxPool2D(3, 2)])


def _inc_c(c7):
    return _IncBranches([
        _inc_conv(192, 1),
        _seq(_inc_conv(c7, 1), _inc_conv(c7, (1, 7), padding=(0, 3)),
             _inc_conv(192, (7, 1), padding=(3, 0))),
        _seq(_inc_conv(c7, 1), _inc_conv(c7, (7, 1), padding=(3, 0)),
             _inc_conv(c7, (1, 7), padding=(0, 3)),
             _inc_conv(c7, (7, 1), padding=(3, 0)),
             _inc_conv(192, (1, 7), padding=(0, 3))),
        _seq(nn.AvgPool2D(3, 1, 1), _inc_conv(192, 1))])


def _inc_d():
    return _IncBranches([
        _seq(_inc_conv(192, 1), _inc_conv(320, 3, 2)),
        _seq(_inc_conv(192, 1), _inc_conv(192, (1, 7), padding=(0, 3)),
             _inc_conv(192, (7, 1), padding=(3, 0)), _inc_conv(192, 3, 2)),
        nn.MaxPool2D(3, 2)])


def _inc_e():
    return _IncBranches([
        _inc_conv(320, 1),
        _seq(_inc_conv(384, 1),
             _IncBranches([_inc_conv(384, (1, 3), padding=(0, 1)),
                           _inc_conv(384, (3, 1), padding=(1, 0))])),
        _seq(_inc_conv(448, 1), _inc_conv(384, 3, padding=1),
             _IncBranches([_inc_conv(384, (1, 3), padding=(0, 1)),
                           _inc_conv(384, (3, 1), padding=(1, 0))])),
        _seq(nn.AvgPool2D(3, 1, 1), _inc_conv(192, 1))])


class Inception3(HybridBlock):
    """Inception V3, 299x299 input (reference:
    model_zoo/vision/inception.py)."""

    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.features = nn.HybridSequential(prefix="")
            self.features.add(
                _inc_conv(32, 3, 2), _inc_conv(32, 3), _inc_conv(64, 3,
                                                                 padding=1),
                nn.MaxPool2D(3, 2),
                _inc_conv(80, 1), _inc_conv(192, 3), nn.MaxPool2D(3, 2),
                _inc_a(32), _inc_a(64), _inc_a(64),
                _inc_b(),
                _inc_c(128), _inc_c(160), _inc_c(160), _inc_c(192),
                _inc_d(),
                _inc_e(), _inc_e(),
                nn.AvgPool2D(8), nn.Dropout(0.5))
            self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def inception_v3(pretrained=False, **kw):
    if pretrained:
        raise MXNetError("pretrained weights unavailable (no egress)")
    return Inception3(**kw)


_MODELS = {
    "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
    "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
    "resnet152_v1": resnet152_v1,
    "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2,
    "resnet50_v2": resnet50_v2, "resnet101_v2": resnet101_v2,
    "resnet152_v2": resnet152_v2,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "alexnet": alexnet,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.5": mobilenet0_5,
    "mobilenetv2_1.0": mobilenet_v2_1_0,
    "squeezenet1.0": squeezenet1_0,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "inceptionv3": inception_v3,
}


def get_model(name, **kwargs):
    """Reference: model_zoo.vision.get_model."""
    name = name.lower()
    if name not in _MODELS:
        raise MXNetError(
            f"unknown model {name!r}; available: {sorted(_MODELS)}")
    return _MODELS[name](**kwargs)
