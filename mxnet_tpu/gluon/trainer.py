"""Trainer: optimizer + kvstore orchestration
(reference: python/mxnet/gluon/trainer.py; SURVEY.md §3.4).

Gradient flow per step: backward fills per-ctx grads → `_allreduce_grads`
sums them across devices through the kvstore (on TPU: one fused XLA
collective for the 'xla' tier) → the optimizer updates each ctx copy.
With a single device the reduce is a no-op and no kvstore is created.

One Optimizer instance is shared by every per-device updater; per-device
update counts are kept separate via ``Optimizer._set_current_context`` so
hyperparameter changes (set_learning_rate, rescale_grad) reach all device
copies while Adam-style step counters do not double-advance.
"""
from __future__ import annotations

import time

from ..base import MXNetError
from .. import optimizer as opt
from .. import runtime_metrics as _rm
from .. import tracing as _tr
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict


# optimizer-state pytree helpers, shared with contrib.fused.FusedTrainStep
def _state_raw(s):
    if s is None:
        return None
    if isinstance(s, (tuple, list)):
        return tuple(_state_raw(x) for x in s)
    return s._data


def _state_sig(s):
    if s is None:
        return None
    if isinstance(s, (tuple, list)):
        return tuple(_state_sig(x) for x in s)
    return (tuple(s.shape), str(s.dtype))


def _state_write_back(dst, new):
    if dst is None:
        return
    if isinstance(dst, (tuple, list)):
        for d, n in zip(dst, new):
            _state_write_back(d, n)
        return
    dst._set_data(new)


def _fused_hyper_refresh(entry, o, params_ordered):
    """Per-step ts/lr/wd/rescale upload with staleness guards — shared
    by the one-program and two-program fused step paths (any divergence
    here silently desynchronizes optimizer schedules between them)."""
    import jax.numpy as jnp
    counts = [o._index_update_count[i] for i, _p in params_ordered]
    if entry.get("ts") is None or entry.get("counts") != counts:
        entry["ts"] = jnp.asarray([float(c) for c in counts], jnp.float32)
    entry["counts"] = [c + 1 for c in counts]
    lrs_py = tuple(float(o._get_lr(i)) for i, _p in params_ordered)
    wds_py = tuple(float(o._get_wd(i)) for i, _p in params_ordered)
    rs_py = float(o.rescale_grad)
    if entry.get("hyper") != (lrs_py, wds_py, rs_py):
        entry["lrs"] = jnp.asarray(lrs_py, jnp.float32)
        entry["wds"] = jnp.asarray(wds_py, jnp.float32)
        entry["rescale"] = jnp.float32(rs_py)
        entry["hyper"] = (lrs_py, wds_py, rs_py)
    return counts


def _fused_rollback(o, params_ordered, prev_num_update, entry, counts):
    """A failed fused step never applied: rewind per-index counts AND
    num_update (advanced via max() in _update_count) so lr schedules
    don't run one step ahead."""
    for i, _p in params_ordered:
        o._index_update_count[i] -= 1
    o.num_update = prev_num_update
    entry["counts"] = counts
    entry["ts"] = None


def _device_capacity_bytes(dev):
    """Usable accelerator memory, from runtime stats when available,
    else a device-kind table (the axon tunnel reports no memory_stats).
    None = unknown (callers must then choose the memory-safe path)."""
    try:
        stats = dev.memory_stats()
    except Exception:       # noqa: BLE001
        stats = None
    if stats and stats.get("bytes_limit"):
        return float(stats["bytes_limit"])
    kind = getattr(dev, "device_kind", "").lower()
    if "lite" in kind or "v5e" in kind:
        return 16e9
    if "v5p" in kind:
        return 95e9
    if "v4" in kind:
        return 32e9         # megacore: one jax device per 32GB chip
    if "v3" in kind:
        return 16e9         # one jax device per TensorCore, 16GB each
    if "v2" in kind:
        return 8e9
    if dev.platform == "cpu":
        return 8e9          # CI-scale assumption; tiny models only
    return None


__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a dict or list of Parameters")
        self._params = []
        for p in params:
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._params.append(p)
        self._compression_params = compression_params
        self._scale = 1.0
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore = None
        self._kv_initialized = False
        self._kvstore_arg = kvstore
        self._update_on_kvstore = update_on_kvstore

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params:
                raise MXNetError(
                    "optimizer_params must be empty when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        # one Updater (state set) per device copy, all driving the SAME
        # optimizer instance (reference: Trainer._init_optimizer)
        self._updater = opt.get_updater(self._optimizer)
        self._dev_updaters = {0: self._updater}

    def _num_ctx(self):
        for p in self._params:
            if p.grad_req != "null":
                return len(p.list_ctx())
        return 1

    def _init_kvstore(self):
        arg = self._kvstore_arg
        multi_ctx = self._num_ctx() > 1
        if arg is None or not multi_ctx:
            # single-device (or explicitly disabled): grads are already the
            # full-batch grads, no cross-device reduce exists
            self._kvstore = None
            if self._update_on_kvstore:
                raise MXNetError(
                    "update_on_kvstore=True requires a kvstore")
            self._update_on_kvstore = False
        else:
            from .. import kvstore as kvs
            store = kvs.create(arg) if isinstance(arg, str) else arg
            if self._compression_params is not None:
                store.set_gradient_compression(self._compression_params)
            update_on_kvstore = self._update_on_kvstore
            if update_on_kvstore is None:
                update_on_kvstore = False
            if update_on_kvstore and not store.is_capable(
                    kvs.KVStoreBase.OPTIMIZER):
                raise MXNetError(
                    f"kvstore type {store.type!r} cannot run the optimizer "
                    f"(update_on_kvstore)")
            self._update_on_kvstore = update_on_kvstore
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    store.init(str(i), p.data())
            if update_on_kvstore:
                store.set_optimizer(self._optimizer)
            self._kvstore = store
        self._kv_initialized = True

    # ---------------------------------------------------------------- props
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # ---------------------------------------------------------------- steps
    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce grads → rescale 1/batch_size → optimizer update
        (reference: Trainer.step).

        When the preceding ``loss.backward()`` deferred a single-CachedOp
        tape (see autograd.backward), the whole backward+update runs as
        ONE donated XLA program here — the three-call recipe at fused-step
        cost."""
        if not _rm._ENABLED:
            self._step_impl(batch_size, ignore_stale_grad)
        else:
            t0 = time.perf_counter()
            try:
                self._step_impl(batch_size, ignore_stale_grad)
            finally:
                # exemplar: a slow step resolves to its trace when the
                # loop runs inside a traced span (serving parity —
                # exemplar_for_quantile(0.99) returns the trace id)
                ctx = _tr.current_context()
                _rm.TRAINER_STEP_SECONDS.observe(
                    time.perf_counter() - t0,
                    exemplar=ctx.trace_id if ctx is not None else None)
            if _rm.grad_norm_enabled():
                self._publish_grad_norm()
        from .. import profiler as _prof
        if _prof._ACTIVE and _prof._state["profile_memory"]:
            _prof.sample_memory()   # per-step live-bytes counter event

    def _step_impl(self, batch_size, ignore_stale_grad):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if self._kvstore is None and self._try_fused_hybrid_step():
            return
        from .. import autograd
        autograd.flush_pending()
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def _publish_grad_norm(self):
        _rm.publish_grad_norm(p.list_grad()[0] for p in self._params
                              if p.grad_req != "null")

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "allreduce_grads() is meaningless with "
                "update_on_kvstore=True")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        keys, grads = [], []
        for i, p in enumerate(self._params):
            if p.grad_req != "null":
                keys.append(str(i))
                grads.append(p.list_grad())
        if not keys:
            return
        if self._update_on_kvstore:
            # optimizer runs on the store's master copy: push grads, the
            # updated weights come back in _update via pull
            self._kvstore.push(keys, grads)
        else:
            # one batched call so the 'xla' tier can bucket-fuse collectives
            self._kvstore.pushpull(keys, grads, out=grads)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "update() cannot be called when update_on_kvstore=True; "
                "use step()")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._update_on_kvstore:
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.pull(str(i), out=p.list_data())
            return
        if self._fused_update():
            return
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            sparse_grad = getattr(p, "_grad_stype", "default") == \
                "row_sparse"
            for j, (w, g) in enumerate(zip(p.list_data(), p.list_grad())):
                if j not in self._dev_updaters:
                    self._dev_updaters[j] = opt.get_updater(self._optimizer)
                self._optimizer._set_current_context(j)
                if sparse_grad:
                    # compress to stored-rows form: the optimizer then
                    # touches only rows this batch actually used
                    g = g.tostype("row_sparse")
                self._dev_updaters[j](i, g, w)
        self._optimizer._set_current_context(0)

    # ------------------------------------------- fused backward+update step
    def _try_fused_hybrid_step(self):
        """Fuse a deferred CachedOp backward with the optimizer update
        into one donated XLA program (VERDICT r2 item 3: the user-facing
        three-call recipe should cost what ShardedTrainer costs).

        Semantics preserved vs the eager path: ``.grad`` buffers are
        still written (as program outputs), update counts advance the
        same way, and any non-parameter leaf (e.g. an attach_grad input)
        gets its grad too.  Falls back to flush+eager on any mismatch.
        """
        from .. import autograd
        pending = autograd.peek_pending()
        if pending is None or not self._fused_eligible():
            return False
        import jax
        import jax.numpy as jnp

        node = pending["node"]
        info = node.fused_info
        items = [(i, p) for i, p in enumerate(self._params)
                 if p.grad_req != "null"]
        if not items:
            return False
        param_by_arr = {}
        for i, p in items:
            try:
                param_by_arr[id(p.data())] = (i, p)
            except Exception:           # noqa: BLE001 — uninitialized etc.
                return False
        # entries: [rng_key] + inputs + params; bwd_impl grads align with
        # entries[1:].  All must be leaves (pure three-call shape).
        entries = node.input_entries
        param_slots, other_slots = {}, []
        for ei, (prod, _oidx, arr) in enumerate(entries):
            if ei == 0:
                continue                # the PRNG key input
            if prod is not None:
                return False
            hit = param_by_arr.get(id(arr))
            if hit is not None:
                param_slots[ei] = hit
            elif arr._grad is not None and arr._grad_req != "null":
                other_slots.append(ei)
        if len(param_slots) != len(items):
            return False                # stale/uncovered params: eager path

        o = self._optimizer
        upd = self._updater
        for i, p in items:
            if i not in upd.states:
                upd.states[i] = o.create_state_multi_precision(i, p.data())

        order = sorted(param_slots)                 # entry index order
        params_ordered = [param_slots[ei] for ei in order]
        weights = [p.data()._data for _i, p in params_ordered]
        states = [_state_raw(upd.states[i]) for i, _p in params_ordered]
        from ..autograd import _node_out_avals
        avals = _node_out_avals(node)
        cots = [g if g is not None else jnp.zeros(a.shape, a.dtype)
                for g, a in zip(node.out_grads, avals)]

        # deferred forward still pending: try the ONE-program path
        # (forward+backward+optimizer; residuals never leave the program)
        if (info.get("fwd_pending") or [False])[0] \
                and info.get("fwd_bwd_impl") is not None:
            handled = self._try_full_fused_step(
                node, info, params_ordered, order, other_slots,
                weights, states, cots)
            if handled:
                return True
            # clean bail: run the standalone forward, then fall through
            # to the two-program backward+optimizer fusion below

        info["materialize_fwd"]()
        res = info["res_holder"][0]

        # cheap cache key: jax.jit re-traces on any aval change, so the
        # per-param shape/dtype signature would only duplicate that at
        # ~10ms host time per step
        key = (id(info["bwd_impl"]), type(o), o._fused_key(),
               tuple(order), tuple(other_slots))
        from collections import OrderedDict
        cache = getattr(self, "_fused_step_progs", None)
        if cache is None:
            cache = self._fused_step_progs = OrderedDict()
        entry = cache.get(key)
        if entry is not None:
            cache.move_to_end(key)      # broken entries too: stay resident
            if entry.get("broken"):
                return False            # negative-cached failing build
        # update counts advance only once fusion is committed (the eager
        # fallback advances its own) — after the broken-entry early out
        prev_num_update = o.num_update
        for i, _p in items:
            o._update_count(i)
        if entry is None:
            bwd_impl = info["bwd_impl"]
            n_entries = len(entries)
            # grad-buffer dtypes baked in: cast INSIDE the program (an
            # eager convert per parameter per step otherwise)
            g_dtypes = tuple(p.data()._grad._data.dtype
                             for _i, p in params_ordered)
            og_dtypes = tuple(entries[ei][2]._grad._data.dtype
                              for ei in other_slots)

            def body(res, cots, weights, states, ts, lrs, wds, rescale):
                grads_all = bwd_impl(list(res), tuple(cots))
                new_w, new_s, pgrads = [], [], []
                for k, ei in enumerate(order):
                    g = grads_all[ei - 1]
                    nw, ns = o._fused_one(weights[k], g, states[k], ts[k],
                                          lrs[k], wds[k], rescale)
                    new_w.append(nw)
                    new_s.append(ns)
                    pgrads.append(g.astype(g_dtypes[k])
                                  if g.dtype != g_dtypes[k] else g)
                ograds = [grads_all[ei - 1].astype(og_dtypes[k])
                          if grads_all[ei - 1].dtype != og_dtypes[k]
                          else grads_all[ei - 1]
                          for k, ei in enumerate(other_slots)]
                return new_w, new_s, ts + 1.0, pgrads, ograds

            # donate residuals (dead after this), weights, states, ts:
            # params update in place at the memory level
            entry = {"prog": jax.jit(body, donate_argnums=(0, 2, 3, 4)),
                     "keepalive": bwd_impl, "n_entries": n_entries}
            cache[key] = entry
            # LRU bound: ragged shapes must not pin evicted CachedOps'
            # backward closures (and their compiled programs) forever
            while len(cache) > 8:
                cache.popitem(last=False)

        counts = _fused_hyper_refresh(entry, o, params_ordered)

        try:
            import warnings
            with warnings.catch_warnings():
                # residuals are donated to be FREED early (they can never
                # alias the outputs); the "not usable" warning is the
                # expected cost of that, not a miss
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable")
                new_w, new_s, new_ts, pgrads, ograds = entry["prog"](
                    list(res), cots, weights, states, entry["ts"],
                    entry["lrs"], entry["wds"], entry["rescale"])
        except BaseException as e:
            # the failed step never applied: never advance schedules
            _fused_rollback(o, params_ordered, prev_num_update,
                            entry, counts)
            entry["ts"] = None
            consumed = any(
                getattr(a, "is_deleted", lambda: False)()
                for a in jax.tree_util.tree_leaves(
                    (res, weights, states)))
            if not consumed and isinstance(e, Exception):
                # pre-donation failure: the deferred tape is untouched —
                # fall back to eager.  Negative-cache ONLY never-succeeded
                # entries (a genuine trace/compile failure); a transient
                # runtime error on a proven program keeps the fused path.
                if not entry.get("succeeded"):
                    entry["broken"] = True
                    warnings.warn(
                        f"fused hybrid step disabled for this signature "
                        f"(falling back to separate backward+update): "
                        f"{e!r}", stacklevel=2)
                return False
            autograd.clear_pending()    # residuals are gone: no replay
            info["consumed"][0] = True
            if isinstance(e, Exception):
                raise MXNetError(
                    "fused hybrid step failed after dispatch; weight, "
                    "optimizer-state and residual buffers were donated "
                    "to the failed program and may be deleted.  Reload "
                    "parameters before continuing.  Cause: "
                    f"{e!r}") from e
            raise   # KeyboardInterrupt/SystemExit propagate as-is
        entry["ts"] = new_ts
        entry["succeeded"] = True
        autograd.clear_pending()
        info["consumed"][0] = True      # residuals donated: no replay
        for (i, p), nw, ns, g in zip(params_ordered, new_w, new_s, pgrads):
            pd = p.data()
            pd._set_data(nw)
            _state_write_back(upd.states[i], ns)
            gb = pd._grad
            gb._set_data(g if g.dtype == gb._data.dtype
                         else jnp.asarray(g, dtype=gb._data.dtype))
        for ei, g in zip(other_slots, ograds):
            gb = entries[ei][2]._grad
            gb._set_data(g if g.dtype == gb._data.dtype
                         else jnp.asarray(g, dtype=gb._data.dtype))
        return True

    def _pick_fused_program(self, info, fpol, make_body, key_arr,
                            nonparams, cots, weights, states):
        """Resolve the save policy for the one-program step and return
        (fwd_bwd_impl, callable program).

        'auto' (the default) AOT-compiles the save-everything variant
        and checks its fitted peak memory against the device capacity:
        save-all reclaims the checkpoint recompute tax (measured +10-15%
        MFU on BERT-large) but would OOM AFTER donation on memory-tight
        models, so it is only chosen when the compiler-reported peak
        fits with margin.  Any probe failure falls back to the
        CachedOp's (memory-safe) policy."""
        import jax
        import jax.numpy as jnp

        factory = info.get("fwd_bwd_factory")
        safe_impl = info["fwd_bwd_impl"]
        if factory is None or fpol == "inherit":
            return safe_impl, jax.jit(make_body(safe_impl),
                                      donate_argnums=(3, 4, 5))
        if fpol != "auto":
            impl = factory(fpol)
            return impl, jax.jit(make_body(impl), donate_argnums=(3, 4, 5))

        try:
            # capacity first: with no capacity estimate the probe result
            # is unusable and the AOT compile (minutes at BERT-large
            # scale) would be pure waste
            cap = _device_capacity_bytes(jax.devices()[0])
            if cap is None:
                return safe_impl, jax.jit(make_body(safe_impl),
                                          donate_argnums=(3, 4, 5))
            impl_all = factory("all")
            jitted = jax.jit(make_body(impl_all), donate_argnums=(3, 4, 5))
            aval = jax.ShapeDtypeStruct
            n = len(weights)
            lowered = jitted.lower(
                key_arr, nonparams, cots, weights, states,
                aval((n,), jnp.float32), aval((n,), jnp.float32),
                aval((n,), jnp.float32), aval((), jnp.float32))
            compiled = lowered.compile()
            ma = compiled.memory_analysis()
            peak = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                    + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
            if peak <= 0.9 * cap:
                # AOT executables are shape-monomorphic, which is fine:
                # a shape change means a new CachedOp signature and
                # therefore a new entry
                return impl_all, compiled
        except Exception:       # noqa: BLE001 — any probe failure: safe
            pass
        return safe_impl, jax.jit(make_body(safe_impl),
                                  donate_argnums=(3, 4, 5))

    def _try_full_fused_step(self, node, info, params_ordered, order,
                             other_slots, weights, states, cots):
        """Deferred-forward fusion: forward+backward+optimizer compiled
        as ONE donated program — the three-call recipe at ShardedTrainer
        shape (no residual HBM round trip between programs).

        Returns True on success.  Returns None to fall back cleanly: the
        forward has NOT run and no state was touched, so the caller's
        two-program (or eager) path proceeds normally.  Raises MXNetError
        only when the program failed after buffer donation."""
        import warnings

        import jax
        import jax.numpy as jnp

        from .. import autograd
        from .block import update_aux_state

        o = self._optimizer
        upd = self._updater
        entries = node.input_entries
        n_entries = len(entries)
        pset = set(order)
        nonparam_slots = [ei for ei in range(1, n_entries)
                          if ei not in pset]
        # the record-time snapshot, NOT live buffers: an input (or param)
        # mutated in place between record() and step() must not change
        # what this step computes — eager and the materialize_fwd
        # fallback both use the recorded values
        raw_in = info["raw_in"]
        key_arr = raw_in[0]
        nonparams = [raw_in[ei] for ei in nonparam_slots]
        weights = [raw_in[ei] for ei in order]

        from ..base import get_env
        fpol = str(get_env("MXNET_FUSED_STEP_SAVE_POLICY", "auto"))
        # cheap cache key: jax.jit itself re-traces on any aval change,
        # so per-param shape/dtype signatures here would only duplicate
        # that at ~10ms of host time per step (the fused path is
        # host-latency sensitive — one python step per ~20ms of chip)
        key = ("full", id(info["fwd_bwd_impl"]), fpol, type(o),
               o._fused_key(), tuple(order), tuple(other_slots),
               tuple(nonparam_slots))
        from collections import OrderedDict
        cache = getattr(self, "_fused_step_progs", None)
        if cache is None:
            cache = self._fused_step_progs = OrderedDict()
        entry = cache.get(key)
        if entry is not None:
            cache.move_to_end(key)
            if entry.get("broken"):
                return None                 # negative-cached failing build
        prev_num_update = o.num_update
        for i, _p in params_ordered:
            o._update_count(i)
        if entry is None:
            ne = n_entries
            p_slots = tuple(order)
            np_slots = tuple(nonparam_slots)
            # grad-buffer dtypes baked in: casting INSIDE the program
            # replaces one eager convert dispatch per parameter per step
            # (~400 host round trips at BERT-large scale)
            g_dtypes = tuple(p.data()._grad._data.dtype
                             for _i, p in params_ordered)
            og_dtypes = tuple(entries[ei][2]._grad._data.dtype
                              for ei in other_slots)

            def make_body(fwd_bwd):
                def body(key, nonparams, cots, weights, states, ts, lrs,
                         wds, rescale):
                    arrays = [None] * (ne - 1)
                    for k, ei in enumerate(p_slots):
                        arrays[ei - 1] = weights[k]
                    for k, ei in enumerate(np_slots):
                        arrays[ei - 1] = nonparams[k]
                    outs, grads_all = fwd_bwd(key, arrays, tuple(cots))
                    new_w, new_s, pgrads = [], [], []
                    for k, ei in enumerate(p_slots):
                        g = grads_all[ei - 1]
                        nw, ns = o._fused_one(weights[k], g, states[k],
                                              ts[k], lrs[k], wds[k],
                                              rescale)
                        new_w.append(nw)
                        new_s.append(ns)
                        pgrads.append(g.astype(g_dtypes[k])
                                      if g.dtype != g_dtypes[k] else g)
                    ograds = [grads_all[ei - 1].astype(og_dtypes[k])
                              if grads_all[ei - 1].dtype != og_dtypes[k]
                              else grads_all[ei - 1]
                              for k, ei in enumerate(other_slots)]
                    return (list(outs), new_w, new_s, ts + 1.0, pgrads,
                            ograds)
                return body

            # donate weights/states/ts: params update in place at the
            # memory level.  Inputs and cotangents are NOT donated (user
            # arrays may be reused across steps).
            fwd_bwd, prog = self._pick_fused_program(
                info, fpol, make_body, key_arr, nonparams, cots,
                weights, states)
            # pin BOTH impls: the cache key uses id(info["fwd_bwd_impl"])
            # and a recycled id after CachedOp-LRU eviction would hit a
            # stale shape-monomorphic entry
            entry = {"prog": prog,
                     "keepalive": (fwd_bwd, info["fwd_bwd_impl"])}
            cache[key] = entry
            while len(cache) > 8:
                cache.popitem(last=False)

        counts = _fused_hyper_refresh(entry, o, params_ordered)

        try:
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable")
                new_outs, new_w, new_s, new_ts, pgrads, ograds = \
                    entry["prog"](key_arr, nonparams, cots, weights,
                                  states, entry["ts"], entry["lrs"],
                                  entry["wds"], entry["rescale"])
        except BaseException as e:
            # the failed step never applied: never advance schedules
            _fused_rollback(o, params_ordered, prev_num_update,
                            entry, counts)
            consumed_bufs = any(
                getattr(a, "is_deleted", lambda: False)()
                for a in jax.tree_util.tree_leaves((weights, states)))
            if not consumed_bufs and isinstance(e, Exception):
                # pre-donation failure (trace/compile): nothing ran, the
                # deferred forward is untouched — negative-cache a
                # never-succeeded build and fall back
                if not entry.get("succeeded"):
                    entry["broken"] = True
                    warnings.warn(
                        f"one-program hybrid step disabled for this "
                        f"signature (falling back to the two-program "
                        f"path): {e!r}", stacklevel=2)
                return None
            # donation happened: weights/states are gone and the deferred
            # outputs can never materialize.  Store the error on each
            # output's var (reference: exception-on-var) — direct reads
            # raise it, while the waitall sweep skips these husks (the
            # failure below is already raised synchronously here)
            autograd.clear_pending()
            info["consumed"][0] = True
            info["fwd_pending"][0] = False
            for out in info.get("outs") or []:
                if out._lazy_cb is not None:
                    out._lazy_cb = None
                    out._var.set_exception(MXNetError(
                        "this output's producing fused step failed after "
                        f"donation; reload parameters.  Cause: {e!r}"))
            if isinstance(e, Exception):
                raise MXNetError(
                    "fused hybrid step failed after dispatch; weight and "
                    "optimizer-state buffers were donated to the failed "
                    "program and may be deleted.  Reload parameters "
                    f"before continuing.  Cause: {e!r}") from e
            raise   # KeyboardInterrupt/SystemExit propagate as-is

        entry["ts"] = new_ts
        entry["succeeded"] = True
        autograd.clear_pending()
        info["consumed"][0] = True
        info["fwd_pending"][0] = False
        outs_nd = info.get("outs") or []
        for out, v in zip(outs_nd, new_outs):
            out._lazy_cb = None
            out._set_data(v)
        n_flat = info["n_flat_out"]
        for p, v in zip(info["aux_params"], new_outs[n_flat:]):
            update_aux_state(p, v, ctx=None)
        for (i, p), nw, ns, g in zip(params_ordered, new_w, new_s,
                                     pgrads):
            pd = p.data()
            pd._set_data(nw)
            _state_write_back(upd.states[i], ns)
            gb = pd._grad
            gb._set_data(g if g.dtype == gb._data.dtype
                         else jnp.asarray(g, dtype=gb._data.dtype))
        for ei, g in zip(other_slots, ograds):
            gb = entries[ei][2]._grad
            gb._set_data(g if g.dtype == gb._data.dtype
                         else jnp.asarray(g, dtype=gb._data.dtype))
        return True

    # ------------------------------------------------------- fused update
    # One XLA program updates every parameter (reference: the multi-tensor
    # update ops + Trainer aggregation).  Eager per-param dispatch costs
    # ~ms of launch latency each on TPU; at hundreds of parameters that
    # dwarfs the update math.  State buffers are donated — the program
    # updates moments in place at the memory level.
    def _fused_eligible(self):
        o = self._optimizer
        if not getattr(o, "fused", False):
            return False
        if self._num_ctx() > 1:
            return False
        for p in self._params:
            if p.grad_req == "null":
                continue
            if getattr(p, "_grad_stype", "default") != "default":
                return False
            if p.grad_req != "write":
                # 'add' grads accumulate across steps; keep the reference
                # per-param path for that rarity
                return False
        return True

    def _fused_update(self):
        if not self._fused_eligible():
            return False
        import jax
        import jax.numpy as jnp
        o = self._optimizer
        upd = self._updater
        items = [(i, p) for i, p in enumerate(self._params)
                 if p.grad_req != "null"]
        if not items:
            return True
        for i, p in items:
            if i not in upd.states:
                upd.states[i] = o.create_state_multi_precision(i, p.data())
            o._update_count(i)

        as_raw, state_sig, write_back = (_state_raw, _state_sig,
                                         _state_write_back)

        weights = [p.data()._data for _, p in items]
        grads = [p.grad()._data for _, p in items]
        states = [as_raw(upd.states[i]) for i, _ in items]

        key = (type(o), o._fused_key(),
               tuple((tuple(w.shape), str(w.dtype), state_sig(upd.states[i]))
                     for (i, _), w in zip(items, weights)))
        cache = getattr(self, "_fused_progs", None)
        if cache is None:
            cache = self._fused_progs = {}
        entry = cache.get(key)
        if entry is None:
            def body(weights, grads, states, ts, lrs, wds, rescale):
                new_w, new_s = [], []
                for k, (w, g, s) in enumerate(zip(weights, grads, states)):
                    nw, ns = o._fused_one(w, g, s, ts[k], lrs[k], wds[k],
                                          rescale)
                    new_w.append(nw)
                    new_s.append(ns)
                # t advances on device: no per-step host->device upload
                return new_w, new_s, ts + 1.0
            # weights, states and ts are donated: the program updates them
            # in place at the memory level (static-alloc semantics); grads
            # are NOT donated — p.grad() stays readable after step()
            entry = {"prog": jax.jit(body, donate_argnums=(0, 2, 3))}
            cache[key] = entry

        # step-varying scalars stay device-resident: re-upload only when
        # the python-side values change (each small upload pays a full
        # host->device round trip, which at TPU dispatch latency would
        # rival the update program itself)
        counts = [o._index_update_count[i] for i, _ in items]
        if entry.get("ts") is None or entry.get("counts") != counts:
            entry["ts"] = jnp.asarray([float(c) for c in counts],
                                      jnp.float32)
        # after the program runs, the donated+incremented device ts equals
        # counts+1 — which is what the python counts will read next step
        entry["counts"] = [c + 1 for c in counts]
        lrs_py = tuple(float(o._get_lr(i)) for i, _ in items)
        wds_py = tuple(float(o._get_wd(i)) for i, _ in items)
        rs_py = float(o.rescale_grad)
        if entry.get("hyper") != (lrs_py, wds_py, rs_py):
            entry["lrs"] = jnp.asarray(lrs_py, jnp.float32)
            entry["wds"] = jnp.asarray(wds_py, jnp.float32)
            entry["rescale"] = jnp.float32(rs_py)
            entry["hyper"] = (lrs_py, wds_py, rs_py)

        new_w, new_s, new_ts = entry["prog"](
            weights, grads, states, entry["ts"], entry["lrs"],
            entry["wds"], entry["rescale"])
        entry["ts"] = new_ts
        for (i, p), nw, ns in zip(items, new_w, new_s):
            p.data()._set_data(nw)
            write_back(upd.states[i], ns)
        return True

    # ---------------------------------------------------------- persistence
    def save_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
            return
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer=False))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as f:
            payload = f.read()
        # restore into EVERY device updater — including ones that have not
        # been lazily created yet (fresh-Trainer resume on multi-ctx params)
        for j in range(self._num_ctx()):
            if j not in self._dev_updaters:
                self._dev_updaters[j] = opt.get_updater(self._optimizer)
        for updater in self._dev_updaters.values():
            updater.set_states(payload)
            updater.optimizer = self._optimizer
