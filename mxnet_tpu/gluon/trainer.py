"""Trainer: optimizer + kvstore orchestration
(reference: python/mxnet/gluon/trainer.py; SURVEY.md §3.4).

Gradient flow per step: backward fills per-ctx grads → `_allreduce_grads`
sums them across devices through the kvstore (on TPU: one fused XLA
collective for the 'xla' tier) → the optimizer updates each ctx copy.
With a single device the reduce is a no-op and no kvstore is created.

One Optimizer instance is shared by every per-device updater; per-device
update counts are kept separate via ``Optimizer._set_current_context`` so
hyperparameter changes (set_learning_rate, rescale_grad) reach all device
copies while Adam-style step counters do not double-advance.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a dict or list of Parameters")
        self._params = []
        for p in params:
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._params.append(p)
        self._compression_params = compression_params
        self._scale = 1.0
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore = None
        self._kv_initialized = False
        self._kvstore_arg = kvstore
        self._update_on_kvstore = update_on_kvstore

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params:
                raise MXNetError(
                    "optimizer_params must be empty when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        # one Updater (state set) per device copy, all driving the SAME
        # optimizer instance (reference: Trainer._init_optimizer)
        self._updater = opt.get_updater(self._optimizer)
        self._dev_updaters = {0: self._updater}

    def _num_ctx(self):
        for p in self._params:
            if p.grad_req != "null":
                return len(p.list_ctx())
        return 1

    def _init_kvstore(self):
        arg = self._kvstore_arg
        multi_ctx = self._num_ctx() > 1
        if arg is None or not multi_ctx:
            # single-device (or explicitly disabled): grads are already the
            # full-batch grads, no cross-device reduce exists
            self._kvstore = None
            if self._update_on_kvstore:
                raise MXNetError(
                    "update_on_kvstore=True requires a kvstore")
            self._update_on_kvstore = False
        else:
            from .. import kvstore as kvs
            store = kvs.create(arg) if isinstance(arg, str) else arg
            if self._compression_params is not None:
                store.set_gradient_compression(self._compression_params)
            update_on_kvstore = self._update_on_kvstore
            if update_on_kvstore is None:
                update_on_kvstore = False
            if update_on_kvstore and not store.is_capable(
                    kvs.KVStoreBase.OPTIMIZER):
                raise MXNetError(
                    f"kvstore type {store.type!r} cannot run the optimizer "
                    f"(update_on_kvstore)")
            self._update_on_kvstore = update_on_kvstore
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    store.init(str(i), p.data())
            if update_on_kvstore:
                store.set_optimizer(self._optimizer)
            self._kvstore = store
        self._kv_initialized = True

    # ---------------------------------------------------------------- props
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # ---------------------------------------------------------------- steps
    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce grads → rescale 1/batch_size → optimizer update
        (reference: Trainer.step)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "allreduce_grads() is meaningless with "
                "update_on_kvstore=True")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        keys, grads = [], []
        for i, p in enumerate(self._params):
            if p.grad_req != "null":
                keys.append(str(i))
                grads.append(p.list_grad())
        if not keys:
            return
        if self._update_on_kvstore:
            # optimizer runs on the store's master copy: push grads, the
            # updated weights come back in _update via pull
            self._kvstore.push(keys, grads)
        else:
            # one batched call so the 'xla' tier can bucket-fuse collectives
            self._kvstore.pushpull(keys, grads, out=grads)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "update() cannot be called when update_on_kvstore=True; "
                "use step()")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        if self._update_on_kvstore:
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.pull(str(i), out=p.list_data())
            return
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            sparse_grad = getattr(p, "_grad_stype", "default") == \
                "row_sparse"
            for j, (w, g) in enumerate(zip(p.list_data(), p.list_grad())):
                if j not in self._dev_updaters:
                    self._dev_updaters[j] = opt.get_updater(self._optimizer)
                self._optimizer._set_current_context(j)
                if sparse_grad:
                    # compress to stored-rows form: the optimizer then
                    # touches only rows this batch actually used
                    g = g.tostype("row_sparse")
                self._dev_updaters[j](i, g, w)
        self._optimizer._set_current_context(0)

    # ---------------------------------------------------------- persistence
    def save_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
            return
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer=False))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            return
        with open(fname, "rb") as f:
            payload = f.read()
        # restore into EVERY device updater — including ones that have not
        # been lazily created yet (fresh-Trainer resume on multi-ctx params)
        for j in range(self._num_ctx()):
            if j not in self._dev_updaters:
                self._dev_updaters[j] = opt.get_updater(self._optimizer)
        for updater in self._dev_updaters.values():
            updater.set_states(payload)
            updater.optimizer = self._optimizer
