"""Trainer: optimizer + kvstore orchestration
(reference: python/mxnet/gluon/trainer.py; SURVEY.md §3.4).

Gradient flow per step: backward fills per-ctx grads → `_allreduce_grads`
sums them across devices through the kvstore (on TPU: XLA collectives) →
the optimizer updates each ctx copy.  With a single device (or with
sharded params under the parallel/pjit path) the reduce is a no-op.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore=None,
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a dict or list of Parameters")
        self._params = []
        self._param2idx = {}
        for i, p in enumerate(params):
            if not isinstance(p, Parameter):
                raise MXNetError(f"invalid parameter {p!r}")
            self._params.append(p)
            self._param2idx[p.name] = i
        self._scale = 1.0
        optimizer_params = optimizer_params or {}
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore = None
        self._kv_initialized = False
        self._kvstore_arg = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updaters = None
        self._states_to_init = True

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params:
                raise MXNetError(
                    "optimizer_params must be empty when optimizer is an "
                    "Optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        # one Updater (state set) per device copy: sharing one state across
        # devices would double-step momentum/Adam statistics
        self._updater = opt.get_updater(self._optimizer)
        self._dev_updaters = {0: self._updater}

    def _init_kvstore(self):
        arg = self._kvstore_arg
        if arg is None or (isinstance(arg, str) and arg == "local"
                           and len(self._params[0].list_ctx()) <= 1):
            # single-device: no kvstore needed
            self._kvstore = None
        else:
            from .. import kvstore as kvs
            self._kvstore = kvs.create(arg) if isinstance(arg, str) else arg
            for i, p in enumerate(self._params):
                if p.grad_req != "null":
                    self._kvstore.init(str(i), p.data())
        self._kv_initialized = True

    # ---------------------------------------------------------------- props
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # ---------------------------------------------------------------- steps
    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce grads → rescale 1/batch_size → optimizer update
        (reference: Trainer.step)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        for i, p in enumerate(self._params):
            if p.grad_req != "null":
                grads = p.list_grad()
                self._kvstore.pushpull(str(i), grads, out=grads)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        import copy
        for i, p in enumerate(self._params):
            if p.grad_req == "null":
                continue
            for j, (w, g) in enumerate(zip(p.list_data(), p.list_grad())):
                if j not in self._dev_updaters:
                    o2 = copy.copy(self._optimizer)
                    # shallow copy shares the count dict: detach it, else
                    # per-device updates still double-advance t
                    o2._index_update_count = dict(
                        self._optimizer._index_update_count)
                    self._dev_updaters[j] = opt.get_updater(o2)
                self._dev_updaters[j](i, g, w)

    # ---------------------------------------------------------- persistence
    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer=False))

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())
