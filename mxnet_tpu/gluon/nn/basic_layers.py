"""Basic neural-network layers (reference: gluon/nn/basic_layers.py).

Each layer's hybrid_forward is built from registered ops, so the same code
runs imperatively, under the CachedOp jit trace, and under pjit sharding.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import Block, HybridBlock, update_aux_state
from ..parameter import Parameter

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "InstanceNorm", "LayerNorm", "GroupNorm", "Embedding", "Flatten",
           "Lambda", "HybridLambda", "Activation", "LeakyReLU", "PReLU",
           "ELU", "SELU", "Swish", "GELU"]


class Sequential(Block):
    """Stack of Blocks executed sequentially (reference: nn.Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
            if isinstance(x, (tuple, list)):
                args = tuple(x[1:])
                x = x[0]
        if args:
            return (x,) + args
        return x

    def __getitem__(self, key):
        children = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*children[key])
            return net
        return children[key]

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock):
    """Hybridizable Sequential (reference: nn.HybridSequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        children = list(self._children.values())
        if isinstance(key, slice):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*children[key])
            return net
        return children[key]

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())

    def infer_shape(self, *args):
        # run children imperatively once; their own deferred init resolves
        x = args[0]
        for block in self._children.values():
            x = block(x)


class Dense(HybridBlock):
    """Fully-connected layer: ``act(dot(x, W.T) + b)``
    (reference: nn.Dense → FullyConnected op)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._activation = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None

    def infer_shape(self, x, *args):
        in_units = x.size // x.shape[0] if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               flatten=self._flatten,
                               no_bias=bias is None)
        if self._activation is not None:
            out = F.Activation(out, act_type=self._activation)
        return out


class Dropout(HybridBlock):
    """Dropout (reference: nn.Dropout). Identity outside train_mode."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        from ... import autograd
        if self._rate == 0 or not autograd.is_training():
            return x
        return F.Dropout(x, p=self._rate, axes=self._axes, mode="training")


class BatchNorm(HybridBlock):
    """Batch normalization with running stats (reference: nn.BatchNorm).

    Training: normalize by batch stats and update running stats (aux
    updates route through update_aux_state so the hybrid trace stays pure).
    Inference: normalize by running stats.
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer, grad_req="null",
                allow_deferred_init=True, differentiable=False)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer, grad_req="null",
                allow_deferred_init=True, differentiable=False)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            p.shape = (c,)

    def cast(self, dtype):
        if str(dtype) in ("float16", "bfloat16"):
            dtype = "float32"  # stats stay fp32 (reference AMP behavior)
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd

        axis = self._axis if self._axis >= 0 else x.ndim + self._axis
        red = tuple(i for i in range(x.ndim) if i != axis)
        bshape = tuple(x.shape[i] if i == axis else 1 for i in range(x.ndim))

        use_batch_stats = autograd.is_training() and \
            not self._use_global_stats
        if use_batch_stats:
            # stats computed through registered ops so the tape (or the
            # hybrid trace) differentiates through them
            mean_nd = x.mean(axis=red)
            xm = x - mean_nd.reshape(bshape)
            var_nd = (xm * xm).mean(axis=red)
            m = self._momentum
            with autograd.pause():
                update_aux_state(
                    self.running_mean,
                    m * running_mean + (1 - m) * mean_nd.detach())
                update_aux_state(
                    self.running_var,
                    m * running_var + (1 - m) * var_nd.detach())
            out = xm / (var_nd.reshape(bshape) + self._eps).sqrt()
        else:
            out = (x - running_mean.reshape(bshape)) / \
                (running_var.reshape(bshape) + self._eps).sqrt()
        if self._scale:
            out = out * gamma.reshape(bshape)
        if self._center:
            out = out + beta.reshape(bshape)
        return out


class LayerNorm(HybridBlock):
    """Layer normalization (reference: nn.LayerNorm → LayerNorm op)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._eps)


class GroupNorm(HybridBlock):
    """Group normalization (reference: nn.GroupNorm)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x, *args):
        c = x.shape[1]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._eps)


class InstanceNorm(HybridBlock):
    """Instance normalization (reference: nn.InstanceNorm)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._eps)


class Embedding(HybridBlock):
    """Index → vector lookup (reference: nn.Embedding → Embedding op)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Flatten(HybridBlock):
    """Collapse all dims but batch (reference: nn.Flatten)."""

    def hybrid_forward(self, F, x):
        return F.flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    """Wrap a function as a Block (reference: nn.Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as _nd
            function = getattr(_nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    """Hybridizable Lambda (reference: nn.HybridLambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func_name = function if isinstance(function, str) else \
            getattr(function, "__name__", "custom")
        self._func = function

    def hybrid_forward(self, F, x, *args):
        f = getattr(F, self._func) if isinstance(self._func, str) \
            else self._func
        if isinstance(self._func, str):
            return f(x, *args)
        return self._func(F, x, *args)


class Activation(HybridBlock):
    """Activation layer (reference: nn.Activation)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, in_channels=1, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as init_mod
        if alpha_initializer is None:
            alpha_initializer = init_mod.Constant(0.25)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(in_channels,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    def __init__(self, approximation="erf", **kwargs):
        super().__init__(**kwargs)
        self._approx = approximation

    def hybrid_forward(self, F, x):
        if self._approx == "tanh":
            return F._contrib_gelu_tanh(x)
        return F._contrib_gelu_erf(x)
