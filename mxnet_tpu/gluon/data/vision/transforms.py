"""Vision transforms (reference: gluon/data/vision/transforms.py)."""
from __future__ import annotations

import numpy as np

from ....base import MXNetError
from .... import ndarray as nd
from ....ndarray import NDArray
from ...block import Block, HybridBlock
from ...nn.basic_layers import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomCrop", "RandomBrightness",
           "RandomContrast", "RandomSaturation", "RandomLighting"]


class Compose(Sequential):
    """Sequentially composed transforms (reference: transforms.Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference: ToTensor)."""

    def hybrid_forward(self, F, x):
        if x.ndim == 3:
            return x.transpose((2, 0, 1)).astype("float32") / 255.0
        return x.transpose((0, 3, 1, 2)).astype("float32") / 255.0


class Normalize(HybridBlock):
    """(x - mean) / std channelwise on CHW (reference: Normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = nd.array(np.asarray(mean, dtype=np.float32)
                              .reshape(-1, 1, 1))
        self._std = nd.array(np.asarray(std, dtype=np.float32)
                             .reshape(-1, 1, 1))

    def hybrid_forward(self, F, x):
        return (x - self._mean) / self._std


def _resize_hwc(x, size, interp=1):
    import jax.image
    if isinstance(size, int):
        size = (size, size)
    w, h = size  # reference convention: size is (width, height)
    method = "nearest" if interp == 0 else "linear"
    out = jax.image.resize(x._data.astype("float32"),
                           (h, w, x.shape[2]), method=method)
    return NDArray(out.astype(x._data.dtype))


class Resize(Block):
    """Resize HWC image (reference: transforms.Resize)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        if self._keep and isinstance(self._size, int):
            h, w = x.shape[0], x.shape[1]
            if w < h:
                size = (self._size, int(h * self._size / w))
            else:
                size = (int(w * self._size / h), self._size)
        else:
            size = self._size
        return _resize_hwc(x, size, self._interpolation)


class CenterCrop(Block):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._interpolation = interpolation

    def forward(self, x):
        w, h = self._size
        H, W = x.shape[0], x.shape[1]
        if H < h or W < w:
            return _resize_hwc(x, self._size, self._interpolation)
        y0, x0 = (H - h) // 2, (W - w) // 2
        return x[y0:y0 + h, x0:x0 + w, :]


class RandomCrop(Block):
    def __init__(self, size, pad=None, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._pad = pad
        self._interpolation = interpolation

    def forward(self, x):
        w, h = self._size
        if self._pad:
            p = self._pad
            x = nd.array(np.pad(x.asnumpy(),
                                ((p, p), (p, p), (0, 0)), mode="constant"),
                         dtype=str(x.dtype))
        H, W = x.shape[0], x.shape[1]
        if H < h or W < w:
            return _resize_hwc(x, self._size, self._interpolation)
        y0 = np.random.randint(0, H - h + 1)
        x0 = np.random.randint(0, W - w + 1)
        return x[y0:y0 + h, x0:x0 + w, :]


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else size
        self._scale = scale
        self._ratio = ratio
        self._interpolation = interpolation

    def forward(self, x):
        H, W = x.shape[0], x.shape[1]
        area = H * W
        for _ in range(10):
            target_area = np.random.uniform(*self._scale) * area
            log_ratio = (np.log(self._ratio[0]), np.log(self._ratio[1]))
            ar = np.exp(np.random.uniform(*log_ratio))
            w = int(round(np.sqrt(target_area * ar)))
            h = int(round(np.sqrt(target_area / ar)))
            if w <= W and h <= H:
                y0 = np.random.randint(0, H - h + 1)
                x0 = np.random.randint(0, W - w + 1)
                crop = x[y0:y0 + h, x0:x0 + w, :]
                return _resize_hwc(crop, self._size, self._interpolation)
        return _resize_hwc(x, self._size, self._interpolation)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return x.flip(axis=1)
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return x.flip(axis=0)
        return x


class _RandomColorJitterBase(Block):
    def __init__(self, jitter):
        super().__init__()
        self._jitter = jitter

    def _alpha(self):
        return 1.0 + np.random.uniform(-self._jitter, self._jitter)


class RandomBrightness(_RandomColorJitterBase):
    def forward(self, x):
        return (x.astype("float32") * self._alpha()).clip(0, 255) \
            .astype(str(x.dtype))


class RandomContrast(_RandomColorJitterBase):
    def forward(self, x):
        xf = x.astype("float32")
        mean = xf.mean()
        a = self._alpha()
        return (xf * a + mean * (1 - a)).clip(0, 255).astype(str(x.dtype))


class RandomSaturation(_RandomColorJitterBase):
    def forward(self, x):
        xf = x.astype("float32")
        gray = xf.mean(axis=2, keepdims=True)
        a = self._alpha()
        return (xf * a + gray * (1 - a)).clip(0, 255).astype(str(x.dtype))


class RandomLighting(Block):
    """AlexNet-style PCA noise (reference: RandomLighting)."""

    _EIGVAL = np.array([55.46, 4.794, 1.148], dtype=np.float32)
    _EIGVEC = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], dtype=np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = np.random.normal(0, self._alpha, size=(3,)).astype(np.float32)
        rgb = (self._EIGVEC * a * self._EIGVAL).sum(axis=1)
        return (x.astype("float32") + nd.array(rgb)).clip(0, 255) \
            .astype(str(x.dtype))
