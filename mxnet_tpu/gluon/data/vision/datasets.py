"""Vision datasets (reference: python/mxnet/gluon/data/vision/datasets.py).

No network egress exists in this environment, so the download step of the
reference is replaced by: (1) load from a local copy if present at
``root``; (2) otherwise generate a deterministic synthetic stand-in with
the same shapes/dtypes/cardinality contract (flagged via ``.synthetic``).
Training-loop code is exercised identically either way.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ....base import MXNetError
from .... import ndarray as nd
from ..dataset import ArrayDataset, Dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._transform = transform
        self._train = train
        self._root = os.path.expanduser(root)
        self.synthetic = False
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


def _synthetic_images(n, shape, num_classes, seed):
    """Deterministic class-correlated images: each class gets a fixed
    random template + noise, so tiny models can actually fit them (keeps
    convergence tests meaningful)."""
    rng = np.random.RandomState(seed)
    templates = rng.uniform(0, 255, size=(num_classes,) + shape)
    labels = rng.randint(0, num_classes, size=(n,)).astype(np.int32)
    noise = rng.uniform(-32, 32, size=(n,) + shape)
    images = np.clip(templates[labels] + noise, 0, 255).astype(np.uint8)
    return images, labels


class MNIST(_DownloadedDataset):
    """MNIST (reference: gluon/data/vision/datasets.py MNIST).

    Items are (image HWC uint8, label int32), image 28x28x1.
    """

    _N_TRAIN, _N_TEST, _SHAPE, _CLASSES = 60000, 10000, (28, 28, 1), 10

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._base_seed = 0x5EED
        super().__init__(root, train, transform)

    def _get_data(self):
        if self._train:
            files = ("train-images-idx3-ubyte.gz",
                     "train-labels-idx1-ubyte.gz")
            n = self._N_TRAIN
        else:
            files = ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")
            n = self._N_TEST
        img_path = os.path.join(self._root, files[0])
        lbl_path = os.path.join(self._root, files[1])
        if os.path.exists(img_path) and os.path.exists(lbl_path):
            with gzip.open(lbl_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                label = np.frombuffer(f.read(), dtype=np.uint8) \
                    .astype(np.int32)
            with gzip.open(img_path, "rb") as f:
                _, _, rows, cols = struct.unpack(">IIII", f.read(16))
                data = np.frombuffer(f.read(), dtype=np.uint8) \
                    .reshape(len(label), rows, cols, 1)
        else:
            self.synthetic = True
            n = min(n, 8192)  # keep the synthetic stand-in light
            data, label = _synthetic_images(
                n, self._SHAPE, self._CLASSES,
                self._base_seed + (0 if self._train else 1))
        self._data = nd.array(data, dtype="uint8")
        self._label = label

    def __getitem__(self, idx):
        img = self._data[idx]
        if self._transform is not None:
            return self._transform(img, self._label[idx])
        return img, self._label[idx]


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        self._base_seed = 0xFA51
        _DownloadedDataset.__init__(self, root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 (reference: datasets.py CIFAR10); items (32x32x3 u8, i32)."""

    _SHAPE, _CLASSES = (32, 32, 3), 10
    _TRAIN_FILES = [f"data_batch_{i}.bin" for i in range(1, 6)]
    _TEST_FILES = ["test_batch.bin"]

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar10"),
                 train=True, transform=None):
        super().__init__(root, train, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as f:
            raw = np.frombuffer(f.read(), dtype=np.uint8)
        rec = raw.reshape(-1, 3072 + self._label_bytes())
        data = rec[:, self._label_bytes():].reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1)
        label = rec[:, self._label_index()].astype(np.int32)
        return data, label

    def _label_bytes(self):
        return 1

    def _label_index(self):
        return 0

    def _get_data(self):
        files = self._TRAIN_FILES if self._train else self._TEST_FILES
        paths = [os.path.join(self._root, f) for f in files]
        if all(os.path.exists(p) for p in paths):
            parts = [self._read_batch(p) for p in paths]
            data = np.concatenate([p[0] for p in parts])
            label = np.concatenate([p[1] for p in parts])
        else:
            self.synthetic = True
            n = 8192 if self._train else 2048
            data, label = _synthetic_images(n, self._SHAPE, self._CLASSES,
                                            0xC1FA + (0 if self._train
                                                      else 1))
        self._data = nd.array(data, dtype="uint8")
        self._label = label


class CIFAR100(CIFAR10):
    _CLASSES = 100
    _TRAIN_FILES = ["train.bin"]
    _TEST_FILES = ["test.bin"]

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _label_bytes(self):
        return 2

    def _label_index(self):
        # CIFAR-100 record: <coarse><fine><3072 px>
        return 1 if self._fine else 0


class ImageFolderDataset(Dataset):
    """A dataset over <root>/<class>/<image> folders
    (reference: ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self.synsets = []
        self.items = []
        if not os.path.isdir(self._root):
            raise MXNetError(f"no such directory {self._root!r}")
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith((".jpg", ".jpeg", ".png", ".npy")):
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from .... import image as img_mod
        path, label = self.items[idx]
        if path.endswith(".npy"):
            img = nd.array(np.load(path), dtype="uint8")
        else:
            img = img_mod.imread(path, self._flag)
        if self._transform is not None:
            return self._transform(img, label)
        return img, label
