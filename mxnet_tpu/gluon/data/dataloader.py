"""DataLoader (reference: python/mxnet/gluon/data/dataloader.py).

TPU-native notes: batches are assembled host-side with NumPy (cheap) and
materialised as a single NDArray per field — one host→device transfer per
batch.  Worker parallelism uses a thread pool rather than the reference's
fork-based multiprocessing: the heavy work (decode/augment) is NumPy
releasing the GIL, and threads avoid re-importing jax per worker.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ...base import MXNetError
from ... import ndarray as nd
from ... import runtime_metrics as _rm
from ...ndarray import NDArray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        return nd.stack_arrays(data, axis=0)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    arr = np.asarray(data)
    return nd.array(arr, dtype=arr.dtype if arr.dtype != np.float64
                    else np.float32)


class DataLoader:
    """Mini-batch iterator over a Dataset (reference: gluon.data.DataLoader)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=False):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError(
                    "batch_size is required when batch_sampler is None")
            if sampler is None:
                sampler = RandomSampler(len(dataset)) if shuffle \
                    else SequentialSampler(len(dataset))
            elif shuffle:
                raise MXNetError("shuffle must be False with custom sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError(
                "batch_size/shuffle/sampler/last_batch are exclusive with "
                "batch_sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        self._pool = ThreadPoolExecutor(self._num_workers) \
            if self._num_workers > 0 else None

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[i] for i in indices])

    def __iter__(self):
        if self._pool is None:
            for indices in self._batch_sampler:
                if _rm._ENABLED:
                    _rm.IO_BATCHES.inc()
                yield self._make_batch(indices)
            return
        # pipelined prefetch through the thread pool
        import collections
        queue = collections.deque()
        it = iter(self._batch_sampler)

        def fill():
            while len(queue) < self._prefetch + 1:
                try:
                    indices = next(it)
                except StopIteration:
                    return
                queue.append(self._pool.submit(self._make_batch, indices))

        fill()
        while queue:
            fut = queue.popleft()
            fill()
            if _rm._ENABLED:
                _rm.IO_BATCHES.inc()
                _rm.IO_PREFETCH_DEPTH.set(len(queue))
            yield fut.result()

    def __len__(self):
        return len(self._batch_sampler)

    def close(self):
        """Shut down the worker pool.  Idempotent; the loader still
        works single-threaded afterwards.  Found by mxlint
        thread-lifecycle: the pool's worker threads are non-daemon, so
        an un-shut-down pool keeps the process alive past the last
        epoch."""
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
