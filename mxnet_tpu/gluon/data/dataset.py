"""Datasets (reference: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

from ...base import MXNetError
from ... import ndarray as nd
from ...ndarray import NDArray

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)

    def filter(self, fn):
        return SimpleDataset([self[i] for i in range(len(self))
                              if fn(self[i])])

    def take(self, count):
        return SimpleDataset([self[i]
                              for i in range(min(count, len(self)))])


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class ArrayDataset(Dataset):
    """Zip of equal-length arrays/lists (reference: ArrayDataset)."""

    def __init__(self, *args):
        if not args:
            raise MXNetError("ArrayDataset needs at least one array")
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            if len(data) != self._length:
                raise MXNetError(
                    f"all arrays must have the same length; arg {i} has "
                    f"{len(data)} != {self._length}")
            if isinstance(data, NDArray) and data.ndim == 1:
                data = data.asnumpy()
            self._data.append(data)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Dataset over a RecordIO file (reference: RecordFileDataset;
    dmlc::RecordIOReader).  Uses the framework's recordio module."""

    def __init__(self, filename):
        from ... import recordio
        self._record = recordio.MXIndexedRecordIO(
            filename[:-4] + ".idx" if filename.endswith(".rec")
            else filename + ".idx", filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
