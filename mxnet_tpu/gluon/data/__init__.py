"""Datasets and data loading (reference: python/mxnet/gluon/data/)."""
from .dataset import Dataset, SimpleDataset, ArrayDataset, RecordFileDataset
from .sampler import Sampler, SequentialSampler, RandomSampler, BatchSampler
from .dataloader import DataLoader, default_batchify_fn
from . import vision

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset",
           "Sampler", "SequentialSampler", "RandomSampler", "BatchSampler",
           "DataLoader", "default_batchify_fn", "vision"]
