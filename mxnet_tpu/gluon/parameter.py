"""Parameter / ParameterDict (reference: python/mxnet/gluon/parameter.py).

TPU-native notes: a Parameter keeps one NDArray per context (the reference's
multi-device copies, SURVEY.md §2.4 P1).  Under the sharded/pjit training
path (mxnet_tpu.parallel) the single copy is a globally-sharded jax.Array
over the device Mesh instead — same object, different placement; nothing in
this class assumes replication.
"""
from __future__ import annotations

import contextvars
from collections import OrderedDict
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..context import Context, current_context
from .. import initializer as init_mod
from .. import ndarray as nd
from ..ndarray import NDArray

__all__ = ["Parameter", "Constant", "ParameterDict",
           "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Raised when a parameter's value is requested before its shape is
    known (reference: deferred initialization in gluon/parameter.py)."""


# While a CachedOp trace is active, parameter reads resolve to the traced
# placeholder values so the compiled program takes params as real inputs
# (otherwise concrete values would be baked in as constants and gradients
# would not flow).  Set by gluon.block.CachedOp.
_PARAM_OVERRIDE: contextvars.ContextVar = contextvars.ContextVar(
    "mx_param_override", default=None)


def _shape_is_known(shape) -> bool:
    if shape is None:
        return False
    return all(s is not None and s > 0 for s in shape)


class Parameter:
    """A weight/bias/state tensor of a Block.

    Supports deferred initialization: unknown dims are 0 until the first
    forward infers them (reference: Parameter._deferred_init).
    """

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self._grad_req = grad_req if differentiable else "null"
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._stype = stype
        # 'row_sparse': Trainer compresses this param's gradient to
        # RowSparse before the optimizer, enabling lazy row updates
        # (reference: Parameter grad_stype for sparse embeddings)
        self._grad_stype = grad_stype
        # per-context storage, keyed by Context
        self._data: "OrderedDict[Context, NDArray]" = OrderedDict()
        self._grad: "OrderedDict[Context, NDArray]" = OrderedDict()
        self._deferred_init = None   # (init, ctx_list, default_init)
        self._var = None

    # ------------------------------------------------------------- properties
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req!r}")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = OrderedDict()
            for arr in self._data.values():
                arr._grad = None
                arr._grad_req = "null"
        elif self._data:
            self._init_grad()

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, " \
               f"dtype={self.dtype})"

    # ---------------------------------------------------------------- init
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Materialise the parameter on ``ctx`` (list ok).

        If the shape is not fully known yet, initialization is deferred
        until the first forward pass infers it.
        """
        if default_init is None:
            default_init = init_mod.Uniform()
        if self._data and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if not _shape_is_known(self.shape):
            if self.allow_deferred_init:
                self._deferred_init = (init, list(ctx), default_init)
                return
            raise MXNetError(
                f"cannot initialize Parameter {self.name!r}: shape "
                f"{self.shape} unknown and allow_deferred_init=False")
        self._finish_init(init, list(ctx), default_init)

    def _finish_init(self, initializer, ctx_list, default_init):
        initializer = initializer or self.init or default_init
        initializer = init_mod.create(initializer)
        from .. import autograd
        with autograd.pause():
            data = nd.zeros(self.shape, dtype=self.dtype, ctx=ctx_list[0])
            initializer(init_mod.InitDesc(self.name), data)
            self._data = OrderedDict()
            for c in ctx_list:
                self._data[c] = data if c == ctx_list[0] \
                    else data.as_in_context(c)
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        from .. import autograd
        with autograd.pause():
            self._grad = OrderedDict()
            for c, arr in self._data.items():
                arr.attach_grad(self._grad_req)
                self._grad[c] = arr.grad

    def _finish_deferred_init(self):
        """Called by the Block once shape inference has filled self.shape."""
        if self._deferred_init is None:
            return
        if not _shape_is_known(self.shape):
            raise DeferredInitializationError(
                f"Parameter {self.name!r} shape still unknown: {self.shape}")
        initializer, ctx_list, default_init = self._deferred_init
        self._finish_init(initializer, ctx_list, default_init)

    # ---------------------------------------------------------------- access
    def _check_initialized(self, ctx=None):
        if self._data:
            if ctx is not None and ctx not in self._data:
                raise MXNetError(
                    f"Parameter {self.name!r} not initialized on {ctx}; "
                    f"it lives on {list(self._data)}")
            return
        if self._deferred_init is not None:
            raise DeferredInitializationError(
                f"Parameter {self.name!r} has deferred initialization "
                f"pending shape inference")
        raise MXNetError(
            f"Parameter {self.name!r} has not been initialized. Call "
            f".initialize() first")

    def data(self, ctx=None) -> NDArray:
        override = _PARAM_OVERRIDE.get()
        if override is not None and self in override:
            return override[self]
        self._check_initialized(ctx)
        if ctx is None:
            return next(iter(self._data.values()))
        return self._data[ctx]

    def list_data(self) -> List[NDArray]:
        self._check_initialized()
        return list(self._data.values())

    def grad(self, ctx=None) -> NDArray:
        if self._grad_req == "null":
            raise MXNetError(f"Parameter {self.name!r} has grad_req='null'")
        self._check_initialized(ctx)
        from .. import autograd
        if autograd._STATE.pending is not None:
            autograd.flush_pending()        # deferred backward: materialize
        if ctx is None:
            return next(iter(self._grad.values()))
        return self._grad[ctx]

    def list_grad(self) -> List[NDArray]:
        self._check_initialized()
        from .. import autograd
        if autograd._STATE.pending is not None:
            autograd.flush_pending()        # deferred backward: materialize
        return list(self._grad.values())

    def list_ctx(self) -> List[Context]:
        if not self._data:
            if self._deferred_init is not None:
                return list(self._deferred_init[1])
            raise MXNetError(f"Parameter {self.name!r} not initialized")
        return list(self._data)

    def set_data(self, data):
        """Set value on every context (reference: Parameter.set_data)."""
        if self.shape is None or not _shape_is_known(self.shape):
            self.shape = tuple(data.shape)
        if self._deferred_init is not None:
            self._finish_deferred_init()
        self._check_initialized()
        if not isinstance(data, NDArray):
            data = nd.array(data, dtype=self.dtype)
        if tuple(data.shape) != tuple(self.shape):
            raise MXNetError(
                f"set_data: shape mismatch for {self.name}: "
                f"{tuple(data.shape)} vs {self.shape}")
        for c, arr in self._data.items():
            arr._set_data(data.as_in_context(c)._data.astype(arr._data.dtype))

    def zero_grad(self):
        if self._grad_req == "null":
            return
        from .. import autograd
        if autograd._STATE.pending is not None:
            autograd.flush_pending()    # grad-writing surface: flush first
        for g in self._grad.values():
            import jax.numpy as jnp
            g._set_data(jnp.zeros_like(g._data))

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data:
            cur = self.data()
            self._data = OrderedDict(
                (c, cur.as_in_context(c)) for c in ctx)
            if self._grad_req != "null":
                self._init_grad()
        elif self._deferred_init is not None:
            i, _, d = self._deferred_init
            self._deferred_init = (i, list(ctx), d)

    def cast(self, dtype):
        self.dtype = dtype
        if not self._data:
            return
        from .. import autograd
        with autograd.pause():
            new = OrderedDict(
                (c, a.astype(dtype)) for c, a in self._data.items())
            self._data = new
            if self._grad_req != "null":
                self._init_grad()

    def var(self):
        """Symbol variable for this parameter (reference: Parameter.var)."""
        if self._var is None:
            from .. import symbol as sym_mod
            self._var = sym_mod.var(self.name, shape=self.shape,
                                    dtype=self.dtype)
        return self._var

    # npz-friendly export used by save_parameters
    def _reduce(self) -> NDArray:
        return self.data()


class Constant(Parameter):
    """Non-differentiable constant parameter (reference: gluon.Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = nd.array(value)
        self.value = value

        class _CInit(init_mod.Initializer):
            def _init_weight(self, _name, arr):
                value.copyto(arr)

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit(),
                         differentiable=False)


class ParameterDict:
    """Ordered dict of Parameters with a shared prefix
    (reference: gluon/parameter.py ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    @property
    def prefix(self):
        return self._prefix

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __contains__(self, name):
        return name in self._params

    def __getitem__(self, name) -> Parameter:
        return self._params[name]

    def __repr__(self):
        body = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict {self._prefix!r} (\n{body}\n)"

    def get(self, name, **kwargs) -> Parameter:
        """Get-or-create by suffix name (prefix is prepended)."""
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            param = Parameter(full, **kwargs)
            self._params[full] = param
        else:
            # reconcile attrs (reference behavior: inherit unknown shape,
            # assert compatibility when both sides are fully known)
            shape = kwargs.get("shape")
            if shape is not None and param.shape is not None:
                if _shape_is_known(param.shape):
                    if (_shape_is_known(shape)
                            and tuple(shape) != tuple(param.shape)):
                        raise MXNetError(
                            f"ParameterDict.get({name!r}): requested shape "
                            f"{tuple(shape)} conflicts with existing shape "
                            f"{tuple(param.shape)} of shared parameter "
                            f"{full!r}")
                else:
                    param.shape = tuple(shape)
        return param

    def get_constant(self, name, value=None) -> Constant:
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            if value is None:
                raise MXNetError(f"no constant {full!r} and no value given")
            param = Constant(full, value)
            self._params[full] = param
        return param

    def _get_impl(self, full_name):
        if full_name in self._params:
            return self._params[full_name]
        if self._shared is not None and full_name in self._shared:
            self._params[full_name] = self._shared[full_name]
            return self._params[full_name]
        return None

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k!r}")
            self._params[k] = v

    # --------------------------------------------------------------- bulk ops
    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = init_mod.Uniform()
        for p in self._params.values():
            p.initialize(None, ctx, default_init=init,
                         force_reinit=force_reinit)

    def zero_grad(self):
        for p in self._params.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self._params.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self._params.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        arrays = {}
        for name, p in self._params.items():
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arrays[name] = p._reduce()
        nd.save(filename, arrays)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        loaded = nd.load(filename)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self._params:
                if name not in loaded:
                    raise MXNetError(
                        f"Parameter {name!r} missing in file {filename!r}")
        for name, value in loaded.items():
            if name not in self._params:
                if ignore_extra:
                    continue
                raise MXNetError(
                    f"Parameter {name!r} in file is not in this dict "
                    f"(use ignore_extra=True to skip)")
            p = self._params[name]
            if p.shape is None or not _shape_is_known(p.shape):
                p.shape = tuple(value.shape)
            if not p._data and p._deferred_init is None:
                p.initialize(ctx=ctx or [current_context()])
            elif p._deferred_init is not None:
                p._finish_deferred_init()
            p.set_data(value)
