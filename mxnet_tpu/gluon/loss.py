"""Loss blocks (reference: python/mxnet/gluon/loss.py)."""
from __future__ import annotations

from ..base import MXNetError
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "HuberLoss", "HingeLoss", "SquaredHingeLoss",
           "LogisticLoss", "TripletLoss", "CosineEmbeddingLoss",
           "PoissonNLLLoss", "CTCLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """Reference: loss.py _apply_weighting."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        if not isinstance(weight, (int, float)):
            raise MXNetError("weight must be a number")
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    """Base loss (reference: gluon.loss.Loss)."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return f"{self.__class__.__name__}(batch_axis={self._batch_axis}, " \
               f"w={self._weight})"

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def _mean_all_but_batch(self, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        if not axes:
            return loss
        return loss.mean(axis=axes)


class L2Loss(Loss):
    r"""``0.5 * (pred - label)^2`` (reference: loss.L2Loss)."""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return self._mean_all_but_batch(loss)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """Numerically-stable BCE over logits (reference:
    loss.SigmoidBinaryCrossEntropyLoss)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            # max(x,0) - x*z + log(1+exp(-|x|)) (stable form)
            loss = F.relu(pred) - pred * label + \
                F.Activation(-F.abs(pred), act_type="softrelu")
            if pos_weight is not None:
                loss = loss + (pos_weight - 1) * label * (
                    F.relu(pred) - pred * label +
                    F.Activation(-F.abs(pred), act_type="softrelu"))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label
                         + F.log(1. - pred + eps) * (1. - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label,
                                         pos_weight)
                         + F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Softmax + CE fused (reference: loss.SoftmaxCrossEntropyLoss)."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -(pred * label).sum(axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise MXNetError(f"bad label_format {label_format!r}")
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return self._mean_all_but_batch(loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        axes = tuple(range(1, pred.ndim))
        loss = (F.square(pred - positive) -
                F.square(pred - negative)).sum(axis=axes) + self._margin
        loss = F.relu(loss)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        eps = 1e-12
        prod = (input1 * input2).sum(axis=-1)
        n1 = F.sqrt(F.square(input1).sum(axis=-1) + eps)
        n2 = F.sqrt(F.square(input2).sum(axis=-1) + eps)
        cos = prod / (n1 * n2)
        label = label.reshape(cos.shape)
        pos = 1.0 - cos
        neg = F.relu(cos - self._margin)
        loss = F.where(label == 1, pos, neg)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None,
                       epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            # Stirling approximation of log(target!)
            stirling = target * F.log(target + epsilon) - target + \
                0.5 * F.log(2 * 3.141592653589793 * (target + epsilon))
            stirling = F.where(target <= 1, F.zeros_like(stirling), stirling)
            loss = loss + stirling
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss.mean()


class CTCLoss(Loss):
    """Connectionist temporal classification loss (reference: loss.CTCLoss;
    src/operator/nn/ctc_loss.cc).  Layout TNC or NTC; blank label = 0 at
    the start of the alphabet ('first' mode)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        if layout not in ("NTC", "TNC"):
            raise MXNetError(f"bad layout {layout!r}")
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = pred.swapaxes(0, 1)  # -> TNC
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)
        # only pass length inputs that exist: literal None would break the
        # symbolic composition/export path (all symbolic inputs are Symbols)
        args, kw = [pred, label], {}
        if pred_lengths is not None:
            args.append(pred_lengths)
            kw["use_data_lengths"] = True
            if label_lengths is not None:
                args.append(label_lengths)
                kw["use_label_lengths"] = True
        elif label_lengths is not None:
            raise MXNetError("CTCLoss: label_lengths requires pred_lengths "
                             "in this build")
        loss = F.CTCLoss(*args, **kw)
        return _apply_weighting(F, loss, self._weight, sample_weight)
