"""Fused recurrent layers (reference: python/mxnet/gluon/rnn/rnn_layer.py).

Parameters are kept per-layer/direction (reference naming: ``l0_i2h_weight``,
``r0_h2h_bias``...) and packed into the flat cudnn-layout vector the fused
``RNN`` op consumes (ops/nn.py; reference src/operator/rnn.cc) at forward
time — the concat is free under XLA fusion.
"""
from __future__ import annotations

from ...base import MXNetError
from ... import ndarray as nd
from ...ndarray import NDArray
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, mode,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"bad layout {layout!r}")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._mode = mode
        self._gates = _GATES[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        with self.name_scope():
            for i in range(num_layers):
                for d in (["l", "r"] if bidirectional else ["l"]):
                    # attribute assignment registers in _reg_params so the
                    # params reach hybrid_forward / the CachedOp trace
                    setattr(self, f"{d}{i}_i2h_weight", self.params.get(
                        f"{d}{i}_i2h_weight", shape=(ng * nh, ni),
                        init=i2h_weight_initializer,
                        allow_deferred_init=True))
                    setattr(self, f"{d}{i}_h2h_weight", self.params.get(
                        f"{d}{i}_h2h_weight", shape=(ng * nh, nh),
                        init=h2h_weight_initializer,
                        allow_deferred_init=True))
                    setattr(self, f"{d}{i}_i2h_bias", self.params.get(
                        f"{d}{i}_i2h_bias", shape=(ng * nh,),
                        init=i2h_bias_initializer,
                        allow_deferred_init=True))
                    setattr(self, f"{d}{i}_h2h_bias", self.params.get(
                        f"{d}{i}_h2h_bias", shape=(ng * nh,),
                        init=h2h_bias_initializer,
                        allow_deferred_init=True))
                ni = nh * self._dir

    def _param_names(self):
        dirs = ["l", "r"] if self._dir == 2 else ["l"]
        weights, biases = [], []
        for i in range(self._num_layers):
            for d in dirs:
                weights.append(f"{d}{i}_i2h_weight")
                weights.append(f"{d}{i}_h2h_weight")
                biases.append(f"{d}{i}_i2h_bias")
                biases.append(f"{d}{i}_h2h_bias")
        return weights + biases

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        """Initial hidden state(s) (reference: _RNNLayer.begin_state)."""
        states = []
        for info in self.state_info(batch_size):
            states.append(func(shape=info["shape"], **kwargs))
        return states

    def infer_shape(self, x, *args):
        ni = x.shape[2] if self._layout == "TNC" else x.shape[2]
        nh, ng = self._hidden_size, self._gates
        dirs = ["l", "r"] if self._dir == 2 else ["l"]
        cur = ni
        for i in range(self._num_layers):
            for d in dirs:
                self.params[self.prefix + f"{d}{i}_i2h_weight"].shape = \
                    (ng * nh, cur)
            cur = nh * self._dir
        self._input_size = ni

    def __call__(self, inputs, states=None, **kwargs):
        if states is None:
            skip_states = True
            batch = inputs.shape[self._layout.index("N")]
            states = self.begin_state(batch, ctx=inputs.context)
        else:
            skip_states = False
            if isinstance(states, NDArray):
                states = [states]
        out = super().__call__(inputs, *states, **kwargs)
        if skip_states:
            return out[0]
        return out[0], list(out[1:])

    def hybrid_forward(self, F, x, *states, **params):
        if self._layout == "NTC":
            x = x.swapaxes(0, 1)
        names = self._param_names()
        flat = F.concat(*[params[n].reshape((-1,)) for n in names], dim=0)
        rnn_args = [x, flat, states[0]]
        if self._mode == "lstm":
            rnn_args.append(states[1])
        outs = F.RNN(*rnn_args, state_size=self._hidden_size,
                     num_layers=self._num_layers, mode=self._mode,
                     bidirectional=self._dir == 2, p=self._dropout,
                     state_outputs=True)
        out = outs[0]
        if self._layout == "NTC":
            out = out.swapaxes(0, 1)
        return (out,) + tuple(outs[1:])

    def __repr__(self):
        return f"{type(self).__name__}({self._hidden_size}, " \
               f"layers={self._num_layers}, bidirectional={self._dir == 2})"


class RNN(_RNNLayer):
    """Vanilla multi-layer RNN (reference: rnn_layer.RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        mode = "rnn_relu" if activation == "relu" else "rnn_tanh"
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, mode, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer (bi)LSTM (reference: rnn_layer.LSTM)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        shape = (self._num_layers * self._dir, batch_size, self._hidden_size)
        return [{"shape": shape, "__layout__": "LNC"},
                {"shape": shape, "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer (bi)GRU (reference: rnn_layer.GRU)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
