"""Recurrent cells (reference: python/mxnet/gluon/rnn/rnn_cell.py).

Cells give step-level control (the reference's unroll API); the fused
layers in rnn_layer.py are the fast path.  ``unroll`` builds a static
python loop — under hybridize the whole unrolled graph compiles to one XLA
program (sequence length is part of the compile signature, the bucketing
model of SURVEY.md §2.4 P8).
"""
from __future__ import annotations

from ...base import MXNetError
from ... import ndarray as nd
from ...ndarray import NDArray
from ..block import HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ModifierCell",
           "ZoneoutCell", "ResidualCell", "BidirectionalCell"]


def _format_sequence(length, inputs, layout, merge):
    """Split/merge TNC|NTC sequences (reference: rnn_cell._format_sequence)."""
    t_axis = layout.index("T")
    batch_axis = layout.index("N")
    if isinstance(inputs, NDArray):
        if length is None:
            length = inputs.shape[t_axis]
        seq = [inputs.slice_axis(axis=t_axis, begin=i, end=i + 1)
               .squeeze(axis=t_axis) for i in range(length)]
    else:
        seq = list(inputs)
    if merge:
        stacked = nd.stack_arrays(seq, axis=t_axis)
        return stacked, t_axis, batch_axis, len(seq)
    return seq, t_axis, batch_axis, len(seq)


class RecurrentCell(HybridBlock):
    """Base recurrent cell (reference: RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        if self._modified:
            raise MXNetError("cannot call begin_state on a modified cell "
                             "(e.g. Zoneout); call on the base cell")
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            states.append(func(shape=info["shape"], **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll for ``length`` steps (reference: RecurrentCell.unroll)."""
        self.reset()
        seq, t_axis, b_axis, length = _format_sequence(
            length, inputs, layout, False)
        if begin_state is None:
            batch = seq[0].shape[b_axis if b_axis < seq[0].ndim else 0]
            begin_state = self.begin_state(seq[0].shape[0],
                                           ctx=seq[0].context)
        states = begin_state
        outputs = []
        for i in range(length):
            out, states = self(seq[i], states)
            outputs.append(out)
        if valid_length is not None:
            stacked = nd.stack_arrays(outputs, axis=layout.index("T"))
            mask = nd.op.sequence_mask(
                stacked.swapaxes(0, 1) if layout == "NTC" else stacked,
                valid_length, use_sequence_length=True, axis=0)
            stacked = mask.swapaxes(0, 1) if layout == "NTC" else mask
            if merge_outputs is False:
                outputs, _, _, _ = _format_sequence(length, stacked,
                                                    layout, False)
            else:
                return stacked, states
        if merge_outputs is None or merge_outputs:
            merged, _, _, _ = _format_sequence(length, outputs, layout, True)
            return merged, states
        return outputs, states

    def __call__(self, inputs, states, **kwargs):
        self._counter += 1
        if isinstance(states, NDArray):
            states = [states]
        return super().__call__(inputs, *states, **kwargs)


class HybridRecurrentCell(RecurrentCell):
    pass


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell (reference: RNNCell)."""

    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, h, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(h, h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        out = F.Activation(i2h + h2h, act_type=self._activation)
        return out, [out]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell, gate order i,f,g,o (reference: LSTMCell)."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, h, c, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        H = self._hidden_size
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=4 * H)
        h2h = F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=4 * H)
        gates = i2h + h2h
        i, f, g, o = F.split(gates, num_outputs=4, axis=-1)
        i, f, o = F.sigmoid(i), F.sigmoid(f), F.sigmoid(o)
        g = F.tanh(g)
        c_new = f * c + i * g
        h_new = o * F.tanh(c_new)
        return h_new, [h_new, c_new]


class GRUCell(HybridRecurrentCell):
    """GRU cell, gate order r,z,n (reference: GRUCell)."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(3 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(3 * hidden_size, hidden_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(3 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(3 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, x, h, i2h_weight, h2h_weight, i2h_bias,
                       h2h_bias):
        H = self._hidden_size
        i2h = F.FullyConnected(x, i2h_weight, i2h_bias, num_hidden=3 * H)
        h2h = F.FullyConnected(h, h2h_weight, h2h_bias, num_hidden=3 * H)
        ir, iz, inn = F.split(i2h, num_outputs=3, axis=-1)
        hr, hz, hn = F.split(h2h, num_outputs=3, axis=-1)
        r = F.sigmoid(ir + hr)
        z = F.sigmoid(iz + hz)
        n = F.tanh(inn + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, [h_new]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (reference: SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        infos = []
        for cell in self._children.values():
            infos.extend(cell.state_info(batch_size))
        return infos

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        states = []
        for cell in self._children.values():
            states.extend(cell.begin_state(batch_size, func, **kwargs))
        return states

    def __call__(self, inputs, states, **kwargs):
        self._counter += 1
        if isinstance(states, NDArray):
            states = [states]
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def forward(self, *args, **kwargs):
        raise MXNetError("SequentialRNNCell is called step-wise, not via "
                         "forward")


class DropoutCell(HybridRecurrentCell):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def hybrid_forward(self, F, x):
        from ... import autograd
        if self._rate > 0 and autograd.is_training():
            x = F.Dropout(x, p=self._rate, axes=self._axes)
        return x, []

    def __call__(self, inputs, states, **kwargs):
        self._counter += 1
        out = HybridBlock.__call__(self, inputs)
        return out[0], states


class ModifierCell(HybridRecurrentCell):
    """Base for cells wrapping another cell (reference: ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__(prefix=base_cell.prefix + self._alias() + "_")
        base_cell._modified = True
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size, func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference: ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def __call__(self, inputs, states, **kwargs):
        from ... import autograd
        self._counter += 1
        next_output, next_states = self.base_cell(inputs, states)
        if not autograd.is_training():
            return next_output, next_states
        from ... import ndarray as _nd_api

        def mask(p, like):
            # framework RNG: respects mx.random.seed and stays stochastic
            # under a jit trace (keys are threaded through trace_key_scope)
            u = _nd_api.random.uniform(0.0, 1.0, shape=like.shape)
            return (u >= p).astype("float32")
        prev = self._prev_output
        if prev is None:
            prev = nd.zeros(next_output.shape)
        if self.zoneout_outputs > 0.:
            m = mask(self.zoneout_outputs, next_output)
            output = m * next_output + (1 - m) * prev
        else:
            output = next_output
        if self.zoneout_states > 0.:
            new_states = []
            for new_s, old_s in zip(next_states, states):
                m = mask(self.zoneout_states, new_s)
                new_states.append(m * new_s + (1 - m) * old_s)
        else:
            new_states = next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds the input to the cell output (reference: ResidualCell)."""

    def _alias(self):
        return "residual"

    def __call__(self, inputs, states, **kwargs):
        self._counter += 1
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Run two cells over the sequence in both directions
    (reference: BidirectionalCell). Only usable via unroll()."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll()")

    def state_info(self, batch_size=0):
        l, r = self._children["l_cell"], self._children["r_cell"]
        return l.state_info(batch_size) + r.state_info(batch_size)

    def begin_state(self, batch_size=0, func=nd.zeros, **kwargs):
        l, r = self._children["l_cell"], self._children["r_cell"]
        return l.begin_state(batch_size, func, **kwargs) + \
            r.begin_state(batch_size, func, **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        seq, t_axis, b_axis, length = _format_sequence(length, inputs,
                                                       layout, False)
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        if begin_state is None:
            begin_state = self.begin_state(seq[0].shape[0],
                                           ctx=seq[0].context)
        def _rev(frames):
            """Per-sample reversal: with valid_length, each sample is
            reversed only within its valid region (reference:
            SequenceReverse with sequence_length) so the backward cell
            never consumes padding before real data."""
            if valid_length is None:
                return list(reversed(frames))
            stacked = nd.stack_arrays(frames, axis=0)   # (T, N, ...)
            rev = nd.op.sequence_reverse(stacked, valid_length,
                                         use_sequence_length=True)
            return [rev[i] for i in range(len(frames))]

        nl = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(
            length, seq, begin_state[:nl], layout="TNC"
            if layout == "TNC" else "NTC", merge_outputs=False,
            valid_length=valid_length)
        r_out, r_states = r_cell.unroll(
            length, _rev(seq), begin_state[nl:],
            layout="TNC" if layout == "TNC" else "NTC",
            merge_outputs=False, valid_length=valid_length)
        outputs = [nd.op.concat(lo, ro, dim=-1)
                   for lo, ro in zip(l_out, _rev(r_out))]
        if merge_outputs is None or merge_outputs:
            merged, _, _, _ = _format_sequence(length, outputs, layout, True)
            return merged, l_states + r_states
        return outputs, l_states + r_states
