"""Gluon: the imperative/hybrid NN API (reference: python/mxnet/gluon/)."""
from . import parameter
from .parameter import Parameter, Constant, ParameterDict, \
    DeferredInitializationError
from . import block
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import loss
from . import trainer
from .trainer import Trainer
from . import utils
from . import data
from . import rnn
from . import contrib
from . import model_zoo

__all__ = ["Parameter", "Constant", "ParameterDict", "Block", "HybridBlock",
           "SymbolBlock", "Trainer", "nn", "rnn", "loss", "data", "utils",
           "contrib", "model_zoo"]
