"""Block / HybridBlock: the Gluon imperative NN API.

Reference: ``python/mxnet/gluon/block.py`` (Block, HybridBlock — whose
``hybridize()`` swaps the python forward for a CachedOp; SURVEY.md §2.2,
§3.3) and ``src/imperative/cached_op.cc`` (the CachedOp backend).

TPU-native redesign of CachedOp: instead of capturing an nnvm graph and
replaying node-by-node through the engine, ``hybridize()`` traces the
block's forward into ONE pure JAX function of (params..., inputs...) and
compiles it with ``jax.jit``, cached by input shape/dtype/train-mode
signature — trace once → XLA executable → replay (SURVEY.md §3.3: "the
single most important path to replicate").  Autograd sees the whole
compiled program as a single tape node, so backward is one XLA program too.
Mutable aux state (BatchNorm running stats) is captured at trace time and
returned as extra outputs (purity restored; XLA donates buffers).
"""
from __future__ import annotations

import contextvars
import functools
import re
import threading
from collections import OrderedDict

import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import Context, current_context
from .. import ndarray as nd
from ..ndarray import NDArray
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock", "nb_cached_programs"]


class _BlockScope(threading.local):
    """Name manager (reference: _BlockScope + NameManager)."""

    def __init__(self):
        self._current = None
        self._counters = {}

    def create(self, prefix, params, hint):
        current = self._current
        if current is None:
            if prefix is None:
                count = self._counters.get(hint, 0)
                self._counters[hint] = count + 1
                prefix = f"{hint}{count}_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._block._scope_counters.get(hint, 0)
            current._block._scope_counters[hint] = count + 1
            prefix = f"{hint}{count}_"
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params


_SCOPE = _BlockScope()


class _NameScope:
    def __init__(self, block):
        self._block = block
        self._old = None

    def __enter__(self):
        self._old = _SCOPE._current
        _SCOPE._current = self
        return self

    def __exit__(self, *exc):
        _SCOPE._current = self._old
        return False


# Aux-state capture for hybrid tracing: while set, Parameter aux updates
# (BatchNorm running stats) are recorded instead of written (they are
# tracers); CachedOp returns them as extra outputs and writes real values.
_AUX_CAPTURE: contextvars.ContextVar = contextvars.ContextVar(
    "mx_aux_capture", default=None)

# True while a CachedOp trace is running: hybridized blocks encountered
# inside the trace run imperatively (they are being inlined into the outer
# compiled program instead of dispatching their own CachedOp).
_TRACING: contextvars.ContextVar = contextvars.ContextVar(
    "mx_hybrid_tracing", default=False)


def update_aux_state(param: Parameter, new_value, ctx=None):
    """Write an auxiliary (non-differentiable) state parameter, routing
    through the hybrid-trace capture when active."""
    cap = _AUX_CAPTURE.get()
    data = new_value._data if isinstance(new_value, NDArray) else new_value
    if cap is not None:
        cap[param] = data
        return
    from .. import autograd
    with autograd.pause():
        for c, arr in param._data.items():
            if ctx is None or c == ctx:
                arr._set_data(data.astype(arr._data.dtype))


class Block:
    """Base class for all neural network layers and models
    (reference: gluon.Block)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _SCOPE.create(prefix, params,
                                                   self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _NameScope(self)
        self._scope_counters = {}
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._reg_params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._forward_hooks = []
        self._forward_pre_hooks = []

    def _alias(self):
        return self.__class__.__name__.lower()

    # ----------------------------------------------------------- attributes
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    @property
    def params(self) -> ParameterDict:
        return self._params

    def name_scope(self):
        return self._scope

    def __repr__(self):
        mods = "\n".join(f"  ({k}): {_indent(repr(v))}"
                         for k, v in self._children.items())
        return f"{self.__class__.__name__}(\n{mods}\n)"

    # ------------------------------------------------------------ parameters
    def collect_params(self, select=None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pat = re.compile(select)
            ret.update({n: p for n, p in self.params.items()
                        if pat.match(n)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer as init_mod
        if init is None:
            init = init_mod.Uniform()
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._reg_params.values():
            p.cast(dtype)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    # ------------------------------------------------------------- save/load
    def save_parameters(self, filename, deduplicate=False):
        """Reference: Block.save_parameters — name-keyed params file."""
        params = self._collect_params_with_prefix()
        arrays = {name: p._reduce() for name, p in params.items()}
        nd.save(filename, arrays)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise MXNetError(
                        f"Parameter {name!r} missing in {filename!r}")
        for name, value in loaded.items():
            if name not in params:
                if ignore_extra:
                    continue
                raise MXNetError(
                    f"Parameter {name!r} in file not found in Block "
                    f"(use ignore_extra=True)")
            p = params[name]
            if p.shape is None or not all(
                    s and s > 0 for s in (p.shape or (0,))):
                p.shape = tuple(value.shape)
            if not p._data:
                p.initialize(ctx=ctx or [current_context()])
            p.set_data(value)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + n: p for n, p in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # --------------------------------------------------------------- forward
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        """Print a per-layer summary table (reference: Block.summary)."""
        rows = []

        def _hook(block, inp, out):
            o = out[0] if isinstance(out, (list, tuple)) else out
            n_params = sum(
                int(_prod(p.shape)) for p in block._reg_params.values()
                if p.shape)
            rows.append((block.name, type(block).__name__,
                         tuple(getattr(o, "shape", ())), n_params))

        handles = []
        for blk in self._iter_blocks():
            blk._forward_hooks.append(_hook)
            handles.append(blk)
        try:
            self(*inputs)
        finally:
            for blk in handles:
                blk._forward_hooks.remove(_hook)
        lines = [f"{'Layer':<30}{'Type':<20}{'Output':<24}{'Params':<12}"]
        total = 0
        for name, typ, shape, npar in rows:
            total += npar
            lines.append(f"{name:<30}{typ:<20}{str(shape):<24}{npar:<12}")
        lines.append(f"Total params: {total}")
        print("\n".join(lines))

    def _iter_blocks(self):
        yield self
        for c in self._children.values():
            yield from c._iter_blocks()


def _indent(s, n=2):
    return s.replace("\n", "\n" + " " * n)


def _prod(t):
    out = 1
    for x in t:
        out *= x
    return out


# ---------------------------------------------------------------------------
# CachedOp: the hybridize() backend (reference: src/imperative/cached_op.cc)
# ---------------------------------------------------------------------------

_N_CACHED_PROGRAMS = 0


def nb_cached_programs():
    """Number of XLA programs compiled by CachedOps (introspection aid)."""
    return _N_CACHED_PROGRAMS


class CachedOp:
    """Trace-compile cache over a HybridBlock's forward.

    Keyed by (input shapes/dtypes, train-mode) — the reference keys its
    per-shape-signature graph passes the same way (cached_op.cc).
    ``static_alloc`` maps to XLA buffer donation (memory reuse); XLA's
    buffer assignment replaces PlanMemory wholesale.
    """

    def __init__(self, block, static_alloc=False, static_shape=False,
                 cache_size=None, bucket_shapes=None):
        from ..base import get_env
        self._block = block
        self._static_alloc = static_alloc
        self._cache = OrderedDict()        # LRU over shape signatures
        if cache_size is None:
            cache_size = int(get_env("MXNET_CACHED_OP_CACHE_SIZE", "16"))
        self._cache_size = max(1, int(cache_size))
        self._n_evictions = 0
        if bucket_shapes is not None:
            bucket_shapes = {int(ax): sorted(int(s) for s in sizes)
                             for ax, sizes in dict(bucket_shapes).items()}
        self._bucket_shapes = bucket_shapes

    def _bucketize(self, inputs):
        """Pad each input's bucketed axes up to the next declared bucket
        size (zeros), collapsing ragged shapes onto a fixed program set.

        Contract (documented at ``hybridize(bucket_shapes=...)``): the
        model must be padding-safe on those axes — mask via
        valid_length/attention masks; outputs keep the padded size.
        """
        from ..ops.registry import LightOpDef, invoke
        out = []
        for x in inputs:
            pads = [(0, 0)] * x.ndim
            changed = False
            for ax, sizes in self._bucket_shapes.items():
                if ax >= x.ndim:
                    continue
                cur = x.shape[ax]
                fit = [s for s in sizes if s >= cur]
                if not fit:
                    raise MXNetError(
                        f"CachedOp bucket_shapes: input axis {ax} has "
                        f"size {cur}, larger than the largest declared "
                        f"bucket {sizes[-1]}")
                if fit[0] != cur:
                    pads[ax] = (0, fit[0] - cur)
                    changed = True
            if changed:
                # pad through the op dispatcher so a TapeNode attaches:
                # input gradients must flow through bucketing (the vjp of
                # pad is slice — padding rows receive no cotangent)
                opdef = LightOpDef(
                    "bucket_pad",
                    functools.partial(jnp.pad, pad_width=tuple(pads)),
                    1, 1)
                x = invoke(opdef, [x], {})
            out.append(x)
        return out

    def __call__(self, inputs, param_list, ctx):
        from .. import autograd
        from ..ops.registry import LightOpDef, invoke

        # probe params before anything else (deferred init must surface
        # before signatures or RNG are touched)
        for _n, p in param_list:
            p.data(ctx)
        if self._bucket_shapes:
            inputs = self._bucketize(inputs)
        sig = (tuple((tuple(x.shape), str(x._data.dtype)) for x in inputs),
               tuple((tuple(p.shape), str(p.dtype)) for _n, p in param_list),
               autograd.is_training())
        entry = self._cache.get(sig)
        if entry is None:
            entry = self._build(inputs, param_list, sig, ctx)
        else:
            self._cache.move_to_end(sig)
        jitted, meta = entry

        from .. import random as mxrand
        # fetch params FIRST: DeferredInitializationError must propagate
        # before any RNG is consumed (keeps the eager/hybrid param-init
        # streams identical)
        param_arrays = [p.data(ctx) for _n, p in param_list]
        # fresh PRNG key each call: random ops inside the trace draw from
        # fold_in(key, counter) so dropout masks differ across steps
        key = NDArray(mxrand.next_key())
        all_in = [key] + list(inputs) + param_arrays
        n_out = meta["n_flat_out"] + len(meta["aux_params"])
        recording = autograd.is_recording()
        if recording:
            outs = self._call_recorded(meta, all_in, n_out, ctx)
        else:
            fn = jitted if n_out > 1 else meta["unwrap1"]
            opdef = LightOpDef(f"cached_op_{self._block.name}", fn,
                               len(all_in), n_out)
            outs = invoke(opdef, all_in, {})
            if n_out == 1:
                outs = [outs]
        flat_outputs = outs[:meta["n_flat_out"]]
        aux_values = outs[meta["n_flat_out"]:]
        for p, v in zip(meta["aux_params"], aux_values):
            if v._lazy_cb is None:      # deferred forward writes aux at
                update_aux_state(p, v, ctx=None)   # materialization/step
        return _unflatten(flat_outputs, meta["tree"])

    def _call_recorded(self, meta, all_in, n_out, ctx):
        """Training-mode dispatch: one forward program that also emits the
        vjp residuals, so backward is one cached program with NO forward
        recompute (reference: CachedOp caches fwd and bwd graphs and keeps
        the saved-tensor buffers between them).

        Deferred-forward mode (after the first recorded call per
        signature): the forward is NOT dispatched here — outputs are
        lazy NDArrays and ``Trainer.step`` compiles
        forward+backward+optimizer into ONE donated program (the
        residuals never round-trip HBM between programs).  Any read of
        an output before step materializes the standalone forward and
        everything degrades to exactly the eager-forward behavior."""
        from .. import autograd
        from ..base import get_env
        from ..engine import engine, is_naive
        for a in all_in:
            if a._lazy_cb is not None:
                a._lazy_materialize()
            a._var.check()
        out_ctx = all_in[1].context if len(all_in) > 1 else None
        consumed = [False]
        res_holder = [None]
        fwd_pending = [False]

        defer = (meta.get("out_avals") is not None
                 and not is_naive()
                 and get_env("MXNET_FUSED_HYBRID_STEP", "1") != "0"
                 and get_env("MXNET_DEFERRED_HYBRID_FWD", "1") != "0")
        if defer:
            fwd_pending[0] = True
            raw_in = [a._data for a in all_in]
            outs = [NDArray._deferred(av, None, ctx=out_ctx)
                    for av in meta["out_avals"]]

            def materialize_fwd(_meta=meta, _raw_in=raw_in):
                """Idempotent standalone-forward fallback (any read
                before step, or a step that can't fuse)."""
                if not fwd_pending[0]:
                    return
                fwd_pending[0] = False
                raw = _meta["fwd_rec"](*_raw_in)
                res_holder[0] = raw[n_out:]
                for o, v in zip(outs, raw[:n_out]):
                    o._lazy_cb = None
                    o._set_data(v)
                for p, v in zip(_meta["aux_params"],
                                raw[_meta["n_flat_out"]:n_out]):
                    update_aux_state(p, NDArray(v), ctx=None)

            for o in outs:
                o._lazy_cb = materialize_fwd
        else:
            raw_in = None
            raw = meta["fwd_rec"](*[a._data for a in all_in])
            vis = raw[:n_out]
            res_holder[0] = raw[n_out:]
            if meta.get("out_avals") is None:
                # unlock deferral from the next recorded call on: the
                # first call runs eagerly so build errors surface here
                meta["out_avals"] = [jax.ShapeDtypeStruct(v.shape, v.dtype)
                                     for v in vis]
            outs = [NDArray(o, ctx=out_ctx) for o in vis]

            def materialize_fwd():
                return None

        def custom_backward(out_grads, in_primals, _meta=meta):
            materialize_fwd()             # deferred fwd: run it standalone
            if consumed[0]:
                raise MXNetError(
                    "backward through this hybridized graph a second "
                    "time: the saved buffers were freed after the first "
                    "pass — call every earlier backward with "
                    "retain_graph=True")
            _res = res_holder[0]
            if autograd.in_retain_backward():
                grads = _meta["bwd_res_retain"](_res, tuple(out_grads))
            else:
                consumed[0] = True        # donating replay frees residuals
                import warnings
                with warnings.catch_warnings():
                    # residuals are donated to be FREED early (they never
                    # alias the grad outputs); the "not usable" warning
                    # is the expected cost of that, not a donation miss
                    warnings.filterwarnings(
                        "ignore",
                        message="Some donated buffers were not usable")
                    grads = _meta["bwd_res"](_res, tuple(out_grads))
            return (None,) + tuple(grads)

        node = autograd.record_custom_node(
            all_in, outs, custom_backward,
            name=f"cached_op_{self._block.name}")
        # fusion hook: Trainer.step may compile this backward (and, when
        # the forward is still pending, the forward too) together with
        # the optimizer update into one donated program (see
        # autograd.backward deferral / Trainer._try_fused_hybrid_step)
        node.fused_info = {"bwd_impl": meta["bwd_impl"],
                           "res_holder": res_holder,
                           "consumed": consumed,
                           "fwd_pending": fwd_pending,
                           "materialize_fwd": materialize_fwd,
                           "fwd_bwd_impl": meta.get("fwd_bwd_impl"),
                           "fwd_bwd_factory": meta.get("fwd_bwd_factory"),
                           "raw_in": raw_in,
                           "outs": outs,
                           "aux_params": meta["aux_params"],
                           "n_flat_out": meta["n_flat_out"]}
        eng = engine()
        if is_naive():
            for o in outs:
                o.wait_to_read()
        for o in outs:
            eng.track(o)
        return outs

    def _build(self, inputs, param_list, sig, ctx):
        global _N_CACHED_PROGRAMS
        from .. import autograd
        from .parameter import _PARAM_OVERRIDE
        block = self._block
        n_in = len(inputs)
        params = [p for _n, p in param_list]
        training = autograd.is_training()
        meta = {"aux_params": [], "n_flat_out": None, "tree": None}

        from .. import random as mxrand

        def pure(key, *arrays):
            xs = [NDArray(a) for a in arrays[:n_in]]
            override = {p: NDArray(a)
                        for p, a in zip(params, arrays[n_in:])}
            tok_t = _TRACING.set(True)
            tok_p = _PARAM_OVERRIDE.set(override)
            tok_a = _AUX_CAPTURE.set(OrderedDict())
            try:
                with mxrand.trace_key_scope(key):
                    with autograd.pause(train_mode=training):
                        out = block.forward(*xs)
                cap = _AUX_CAPTURE.get()
            finally:
                _AUX_CAPTURE.reset(tok_a)
                _PARAM_OVERRIDE.reset(tok_p)
                _TRACING.reset(tok_t)
            flat, tree = _flatten(out)
            meta["aux_params"] = list(cap.keys())
            meta["n_flat_out"] = len(flat)
            meta["tree"] = tree
            return tuple(x._data for x in flat) + tuple(cap.values())

        # Trace eagerly once via eval_shape so meta is filled determinately
        # before the jitted callable is used (jit traces lazily).  The key
        # here is a constant dummy (eval_shape executes nothing): the
        # global RNG stream must not advance during meta-tracing.
        jax.eval_shape(pure, jax.random.PRNGKey(0),
                       *[x._data for x in inputs],
                       *[p.data(ctx)._data for p in params])
        jitted = jax.jit(pure)
        meta["unwrap1"] = lambda *arrays: jitted(*arrays)[0]

        # Training path: forward and backward as one cached program pair
        # sharing saved residuals (reference: CachedOp caches the fwd and
        # bwd graphs; saved tensors live between them).  The vjp closure is
        # flattened into plain arrays to cross the jit boundary; its static
        # treedef is captured as a trace-time side effect.  Replaying
        # backward through this program costs zero recompute and exactly
        # one dispatch.
        # What the training forward saves for backward is a memory/compute
        # dial (reference: MXNET_BACKWARD_DO_MIRROR memory mirroring):
        #   all            — save every intermediate (vjp default; hostile
        #                    to HBM at BERT-large scale: fp32 attention
        #                    probs alone are GBs)
        #   dots (default) — save matmul/conv outputs, recompute elementwise
        #                    (XLA refuses nothing the MXU already paid for)
        #   dots_no_batch  — save only weight-side matmuls; activation
        #                    matmuls (attention) recompute
        #   none           — full rematerialization, minimal memory
        from ..base import get_env
        policy_name = get_env("MXNET_CACHED_OP_SAVE_POLICY")
        policies = {
            "all": None,
            "dots": jax.checkpoint_policies.dots_saveable,
            "dots_no_batch":
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            "none": jax.checkpoint_policies.nothing_saveable,
        }
        policy = policies.get(str(policy_name), policies["dots_no_batch"])

        @jax.jit
        def fwd_rec(key, *arrays):
            fn = lambda *arr: pure(key, *arr)      # noqa: E731
            if policy is not None:
                fn = jax.checkpoint(fn, policy=policy)
            outs, vjp_fn = jax.vjp(fn, *arrays)
            flat, tree = jax.tree_util.tree_flatten(vjp_fn)
            meta["res_tree"] = tree
            return tuple(outs) + tuple(flat)

        def bwd_impl(res, cots):
            vjp_fn = jax.tree_util.tree_unflatten(meta["res_tree"],
                                                  list(res))
            # key is closed over in fwd_rec's lambda: grads cover
            # inputs+params only; _call_recorded prepends None for the key
            return vjp_fn(tuple(cots))

        def _make_fwd_bwd_impl(p):
            def fwd_bwd_impl(key, arrays, cots):
                """Whole fwd+bwd as one traceable body (un-jitted): the
                deferred-forward step fusion embeds this next to the
                optimizer update so residuals stay program-internal."""
                fn = lambda *arr: pure(key, *arr)      # noqa: E731
                if p is not None:
                    fn = jax.checkpoint(fn, policy=p)
                outs, vjp_fn = jax.vjp(fn, *arrays)
                grads = vjp_fn(tuple(cots))
                return outs, grads
            return fwd_bwd_impl

        # the ONE-program step can afford a more generous save policy
        # than the two-program path (residuals are program-internal,
        # freed as consumed, not materialized program outputs) — the
        # factory lets Trainer pick per MXNET_FUSED_STEP_SAVE_POLICY,
        # including the memory-probed 'auto' mode
        fwd_bwd_impl = _make_fwd_bwd_impl(policy)

        meta["fwd_rec"] = fwd_rec
        meta["fwd_bwd_impl"] = fwd_bwd_impl
        meta["fwd_bwd_factory"] = \
            lambda name: _make_fwd_bwd_impl(policies.get(str(name), policy))
        meta["bwd_impl"] = bwd_impl        # un-jitted: Trainer step fusion
        # residuals are dead after one replay: donating them lets XLA free
        # each saved tensor as soon as its consuming bwd op runs (the
        # reference frees saved tensors the same way).  retain_graph=True
        # backward uses the non-donating twin so a second replay works.
        meta["bwd_res"] = jax.jit(bwd_impl, donate_argnums=(0,))
        meta["bwd_res_retain"] = jax.jit(bwd_impl)
        _N_CACHED_PROGRAMS += 1
        entry = (jitted, dict(meta))
        self._cache[sig] = entry
        while len(self._cache) > self._cache_size:
            self._cache.popitem(last=False)       # evict LRU program
            self._n_evictions += 1
            if self._n_evictions in (1, 10, 100, 1000):
                import warnings
                warnings.warn(
                    f"CachedOp for {self._block.name!r}: "
                    f"{self._n_evictions} compiled-program eviction(s) — "
                    f"ragged input shapes are forcing recompiles.  "
                    f"Declare hybridize(bucket_shapes={{axis: [sizes]}}) "
                    f"to pad onto a fixed bucket set, or raise "
                    f"MXNET_CACHED_OP_CACHE_SIZE "
                    f"(now {self._cache_size}).", stacklevel=3)
        return entry


def _flatten(out):
    if isinstance(out, NDArray):
        return [out], None
    if isinstance(out, (list, tuple)):
        flat, tree = [], []
        for o in out:
            f, t = _flatten(o)
            flat.extend(f)
            tree.append((len(f), t))
        return flat, tree
    raise MXNetError(f"hybrid_forward returned unsupported type {type(out)}")


def _unflatten(flat, tree):
    if tree is None:
        return flat[0]
    out, i = [], 0
    for n, sub in tree:
        chunk = flat[i:i + n]
        out.append(_unflatten(chunk, sub))
        i += n
    return tuple(out)


class HybridBlock(Block):
    """A Block that can be traced and compiled (reference: HybridBlock).

    Subclasses implement ``hybrid_forward(self, F, x, *args, **params)``
    where registered parameters arrive as keyword NDArrays.  Before
    ``hybridize()`` it runs imperatively (op-by-op, full python
    debuggability); after, the whole forward is one compiled XLA program.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  cache_size=None, bucket_shapes=None, **kwargs):
        """Swap the python forward for a compiled CachedOp.

        ``cache_size``: bound on compiled programs kept per CachedOp
        (default env ``MXNET_CACHED_OP_CACHE_SIZE``, 16); LRU-evicted
        beyond that, with a churn warning.  ``bucket_shapes``: optional
        ``{axis: [sizes]}`` — inputs are zero-padded up along those axes
        to the next declared size so ragged shapes share programs
        (BucketingModule's policy for the Gluon layer).  The model must
        be padding-safe on bucketed axes (mask via valid_length etc.);
        outputs keep the padded size.
        """
        self._active = active
        self._flags = {"static_alloc": static_alloc,
                       "static_shape": static_shape,
                       "cache_size": cache_size,
                       "bucket_shapes": bucket_shapes}
        self._cached_op = None
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def infer_shape(self, *args):
        """Override in layers that support deferred parameter init."""
        raise DeferredInitializationError(
            f"{type(self).__name__} cannot infer parameter shapes; "
            f"provide explicit in_units/in_channels or run a forward pass")

    def _get_ctx(self, args):
        for a in args:
            if isinstance(a, NDArray):
                return a.context
        return current_context()

    def _param_items(self):
        # ALL descendant params are inputs of the compiled program (child
        # blocks resolve theirs through the trace-time override).
        return list(self.collect_params().items())

    def forward(self, x, *args, **kwargs):
        if not isinstance(x, NDArray):
            # symbolic composition path: build a Symbol graph
            from ..symbol import Symbol
            if isinstance(x, Symbol):
                from .. import symbol as sym_mod
                pvars = {n: p.var() for n, p in self._reg_params.items()}
                return self.hybrid_forward(sym_mod, x, *args, **pvars,
                                           **kwargs)
            raise MXNetError(
                f"forward expects NDArray or Symbol, got {type(x)}")
        ctx = self._get_ctx((x,) + args)
        try:
            pdata = {n: p.data(ctx) for n, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._finish_deferred(x, *args)
            pdata = {n: p.data(ctx) for n, p in self._reg_params.items()}

        if self._active and not _TRACING.get() and not kwargs \
                and all(isinstance(a, NDArray) for a in args):
            if self._cached_op is None:
                self._cached_op = CachedOp(self, **self._flags)
            try:
                return self._cached_op([x] + list(args),
                                       self._param_items(), ctx)
            except DeferredInitializationError:
                # child params deferred: run ONE imperative pass to infer
                # shapes; suppress child CachedOps during it (they would
                # compile throwaway programs)
                tok = _TRACING.set(True)
                try:
                    return self.hybrid_forward(nd, x, *args, **pdata,
                                               **kwargs)
                finally:
                    _TRACING.reset(tok)
        return self.hybrid_forward(nd, x, *args, **pdata, **kwargs)

    def _finish_deferred(self, *args):
        self.infer_shape(*args)
        for p in self._reg_params.values():
            if p._deferred_init is not None:
                p._finish_deferred_init()

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def optimize_for(self, x, *args, backend=None, **backend_opts):
        """Trace this block to a Symbol graph, run the registered
        subgraph-backend pass over it, and return a ``SymbolBlock``
        sharing this block's parameters (reference:
        HybridBlock.optimize_for).

        Upstream rewrites the cached graph in place; here the compiled
        path is an XLA trace (which already fuses), so the pass runs on
        the exported Symbol DAG and the optimized graph comes back as a
        new block — same parameters, rewritten topology.
        """
        from .. import symbol as sym_mod
        if backend is None:
            raise MXNetError("optimize_for requires backend=<name>")
        n_in = 1 + len(args)
        data_syms = [sym_mod.var("data")] if n_in == 1 else \
            [sym_mod.var(f"data{i}") for i in range(n_in)]
        out = self(*data_syms)
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        opt = out.optimize_for(backend, **backend_opts)
        blk = SymbolBlock(opt, data_syms, params=self.collect_params())
        # example data validates the rewritten graph end-to-end
        blk(x, *args)
        return blk

    # ------------------------------------------------------------ export
    def export(self, path, epoch=0):
        """Serialize to symbol-json + params (reference: HybridBlock.export).

        Builds the symbolic graph by running hybrid_forward with Symbol
        inputs (reference: _build_cache's symbol trace)."""
        from .. import symbol as sym_mod
        data = sym_mod.var("data")
        out = self(data)
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        sym_file = f"{path}-symbol.json"
        out.save(sym_file)
        params = {}
        for name, p in self.collect_params().items():
            params[name] = p._reduce()
        nd.save(f"{path}-{epoch:04d}.params", params)
        return sym_file

    def export_stablehlo(self, *example_inputs, path, emit_text=False,
                         dynamic_batch=False, version=None,
                         precompile=(), quantize=None):
        """Export this block's inference forward as a self-contained
        StableHLO artifact (``deploy.export_stablehlo``): weights baked
        in, ``path.json`` serving-signature manifest alongside.  Pass
        ``dynamic_batch=True`` to leave the batch dimension symbolic so
        ``mxnet_tpu.serving`` can shape-bucket request batches over one
        artifact; ``version`` tags the manifest for repository
        hot-swap; ``precompile`` (bucket list, or True for the serving
        defaults) ships AOT-compiled executables next to the manifest
        so a matching-topology server starts with zero XLA compiles;
        ``quantize='int8'|'fp8'`` ships the quantized serving shape
        (weights packed to 1 byte with per-tensor scales in the
        manifest v4 ``quantization`` block, example inputs doubling as
        the calibration batch — docs/serving.md §7)."""
        from .. import deploy
        return deploy.export_stablehlo(
            self, *example_inputs, path=path, emit_text=emit_text,
            dynamic_batch=dynamic_batch, version=version,
            precompile=precompile, quantize=quantize)


class SymbolBlock(HybridBlock):
    """Wrap a Symbol graph as a Block (reference: gluon.SymbolBlock)."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from .. import symbol as sym_mod
        from ..symbol import Symbol
        if isinstance(outputs, (list, tuple)):
            outputs = sym_mod.Group(list(outputs))
        if isinstance(inputs, Symbol):
            inputs = [inputs]
        self._out_sym = outputs
        self._in_names = [s.name for s in inputs]
        in_set = set(self._in_names)
        for arg in outputs.list_arguments():
            if arg in in_set:
                continue
            # graph argument names are raw Parameter names; adopt a
            # matching shared parameter directly rather than minting a
            # fresh (prefixless) one through get()'s prefixed lookup
            if params is not None and arg in params:
                self._params._params[arg] = params[arg]
            else:
                self._params.get(arg, shape=None, allow_deferred_init=True)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as sym_mod
        out = sym_mod.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [sym_mod.var(n) for n in input_names]
        blk = SymbolBlock(out, inputs)
        if param_file is not None:
            loaded = nd.load(param_file)
            for name, value in loaded.items():
                if name in blk._params:
                    p = blk._params[name]
                    p.shape = tuple(value.shape)
                    p.initialize(ctx=ctx or [current_context()])
                    p.set_data(value)
        return blk

    def forward(self, *args):
        ctx = self._get_ctx(args)
        bindings = dict(zip(self._in_names, args))
        for name, p in self._params.items():
            if name not in bindings:
                bindings[name] = p.data(ctx)
        outs = self._out_sym.eval(**bindings)
        return outs[0] if len(outs) == 1 else list(outs)
