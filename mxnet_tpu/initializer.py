"""Weight initializers (reference: python/mxnet/initializer.py).

Same registry + string-alias UX as the reference (``init="xavier"``), drawing
from the framework RNG so ``mx.random.seed`` controls initialization.
"""
from __future__ import annotations

import json
import math
import types

import jax
import jax.numpy as jnp
import numpy as np

from .base import Registry, MXNetError

__all__ = ["Initializer", "Uniform", "Normal", "Constant", "Zero", "One",
           "Xavier", "MSRAPrelu", "Orthogonal", "LSTMBias", "Bilinear",
           "create", "register"]

_REG = Registry("initializer")


def register(klass):
    _REG.register(klass.__name__.lower(), klass, override=True)
    return klass


class InitDesc(str):
    """Parameter-name descriptor carrying attrs (reference: InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer; callable on (name, NDArray)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __call__(self, name, arr):
        from .ndarray import NDArray
        if not isinstance(name, str):
            name, arr = getattr(name, "name", str(name)), name
        name_l = name.lower() if isinstance(name, str) else ""
        if name_l.endswith("gamma"):
            self._init_one(arr)
        elif name_l.endswith("beta") or name_l.endswith("bias"):
            self._init_zero(arr)
        elif "running_mean" in name_l or "moving_mean" in name_l:
            self._init_zero(arr)
        elif "running_var" in name_l or "moving_var" in name_l:
            self._init_one(arr)
        else:
            self._init_weight(name, arr)

    def init_weight(self, name, arr):
        self._init_weight(name, arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError

    @staticmethod
    def _init_zero(arr):
        arr._set_data(jnp.zeros_like(arr._data))

    @staticmethod
    def _init_one(arr):
        arr._set_data(jnp.ones_like(arr._data))


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(arr)


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(arr)


# string aliases used throughout gluon layer defaults (reference accepts
# both "zeros" and "zero")
_REG.register("zeros", Zero, override=True)
_REG.register("ones", One, override=True)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr._set_data(jnp.full_like(arr._data, self.value))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        from . import random as mxrand
        k = mxrand.next_key()
        arr._set_data(jax.random.uniform(
            k, arr.shape, minval=-self.scale, maxval=self.scale,
            dtype=arr._data.dtype))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        from . import random as mxrand
        k = mxrand.next_key()
        arr._set_data(self.sigma * jax.random.normal(
            k, arr.shape, dtype=arr._data.dtype))


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference: initializer.py Xavier)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        from . import random as mxrand
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            fan_in = fan_out = shape[0] if shape else 1
        else:
            if len(shape) > 2:
                hw_scale = float(np.prod(shape[2:]))
            fan_in = shape[1] * hw_scale
            fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        else:
            factor = fan_out
        scale = math.sqrt(self.magnitude / factor)
        k = mxrand.next_key()
        if self.rnd_type == "uniform":
            arr._set_data(jax.random.uniform(
                k, shape, minval=-scale, maxval=scale,
                dtype=arr._data.dtype))
        else:
            arr._set_data(scale * jax.random.normal(
                k, shape, dtype=arr._data.dtype))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init_weight(self, name, arr):
        from . import random as mxrand
        shape = arr.shape
        flat = (shape[0], int(np.prod(shape[1:])))
        a = jax.random.normal(mxrand.next_key(), flat)
        q, r = jnp.linalg.qr(a if flat[0] <= flat[1] else a.T)
        q = q if flat[0] <= flat[1] else q.T
        q = q * jnp.sign(jnp.diagonal(r))[..., None] if q.shape[0] == r.shape[0] else q
        arr._set_data((self.scale * q[:flat[0], :flat[1]]).reshape(shape)
                      .astype(arr._data.dtype))


@register
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (reference: initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        n = arr.shape[0] // 4
        b[n:2 * n] = self.forget_bias  # gate order i, f, g, o
        arr._set_data(jnp.asarray(b, dtype=arr._data.dtype))


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        shape = arr.shape
        weight = np.zeros(shape, dtype=np.float32)
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data(jnp.asarray(weight, dtype=arr._data.dtype))


class Mixed:
    """Per-pattern initializer dispatch (reference: Mixed)."""

    def __init__(self, patterns, initializers):
        import re
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(f"parameter {name} did not match any pattern")


def create(init, **kwargs):
    if isinstance(init, Initializer):
        return init
    if callable(init):
        return init
    if isinstance(init, str):
        klass = _REG.find(init.lower())
        if klass is None:
            raise MXNetError(f"unknown initializer {init!r}; "
                             f"known: {_REG.list_names()}")
        return klass(**kwargs)
    raise MXNetError(f"cannot create initializer from {init!r}")


# expose `mx.init.*` namespace alias
init = types.ModuleType(__name__ + ".init")
for _n in __all__:
    setattr(init, _n, globals()[_n])
init.InitDesc = InitDesc
init.Mixed = Mixed
import sys as _sys

_sys.modules[init.__name__] = init
