"""Subgraph backends: registered graph-rewrite passes + ``optimize_for``.

Reference surface: the subgraph API in ``src/operator/subgraph/``
(``SubgraphProperty`` registry, ``MXSetSubgraphPropertyOpNames``) and its
frontends ``Symbol.optimize_for(backend)`` / ``HybridBlock.optimize_for``
— SURVEY.md §2.1 nnvm-passes row ("subgraph API, SubgraphProperty") and
the oneDNN/TensorRT glue row.

TPU-native redesign: upstream subgraph backends exist mostly to hand
fused kernels to cuDNN/oneDNN/TensorRT; on this build XLA performs that
fusion automatically, so the registry's built-in passes do the graph
hygiene XLA cannot see — stripping train-only ops for inference
(``"inference"``) — while the registry itself gives users the same
extension point upstream had: register a property, rewrite the DAG.
Passes operate on the pure-python ``Symbol`` DAG (``_SymNode``), so a
custom property is a dozen lines instead of a C++ plugin.
"""
from __future__ import annotations

from typing import Callable, Dict

from .base import MXNetError

__all__ = ["SubgraphProperty", "register_backend", "get_backend",
           "list_backends", "optimize_symbol", "rewrite_nodes"]

_BACKENDS: Dict[str, "SubgraphProperty"] = {}


class SubgraphProperty:
    """One graph-rewrite backend (reference: SubgraphProperty).

    Subclass and override :meth:`apply`, then register::

        @register_backend("my_backend")
        class MyProp(SubgraphProperty):
            def apply(self, sym, **kwargs):
                return rewrite_nodes(sym, my_node_fn)
    """

    name: str = ""

    def apply(self, sym, **kwargs):
        """Return the rewritten Symbol (must not mutate ``sym``)."""
        raise NotImplementedError


def register_backend(name: str):
    """Register a SubgraphProperty class or factory under ``name``."""

    def deco(cls):
        prop = cls() if isinstance(cls, type) else cls
        if not isinstance(prop, SubgraphProperty):
            raise MXNetError("register_backend expects a SubgraphProperty")
        prop.name = name
        _BACKENDS[name] = prop
        return cls

    return deco


def get_backend(name: str) -> SubgraphProperty:
    if name not in _BACKENDS:
        raise MXNetError(
            f"unknown subgraph backend {name!r} "
            f"(registered: {sorted(_BACKENDS)})")
    return _BACKENDS[name]


def list_backends():
    return sorted(_BACKENDS)


def optimize_symbol(sym, backend: str, **kwargs):
    """Apply a registered backend pass to ``sym`` (Symbol.optimize_for)."""
    return get_backend(backend).apply(sym, **kwargs)


# --------------------------------------------------------------------------
# Rewrite helper
# --------------------------------------------------------------------------

def rewrite_nodes(sym, node_fn: Callable):
    """Rebuild the DAG applying ``node_fn`` to every op node.

    ``node_fn(node, new_inputs) -> None | (node_ref, out_idx) | _SymNode``
      * ``None``: keep the node (with rewritten inputs)
      * ``(ref, idx)``: REPLACE the node's output 0 by that existing
        entry (e.g. skip an identity by returning its input entry)
      * a new ``_SymNode``: substitute it

    Only single-output replacements are supported for elision; nodes with
    ``num_outputs > 1`` are always kept (rewritten inputs only).
    """
    from .symbol.symbol import Symbol, _SymNode

    memo = {}
    for node in sym._topo():                   # producers first, iterative
        if node.is_variable:
            memo[id(node)] = {0: (node, 0)}
            continue
        new_inputs = [memo[id(n)][i] for n, i in node.inputs]
        result = node_fn(node, new_inputs) if node.num_outputs == 1 \
            else None
        if result is None:
            new = _SymNode(node.op, new_inputs, node.kwargs, node.name,
                           node.num_outputs)
            new.attrs = dict(node.attrs)
            entry_map = {i: (new, i) for i in range(node.num_outputs)}
        elif isinstance(result, tuple):
            entry_map = {0: result}
        else:
            entry_map = {i: (result, i) for i in range(result.num_outputs)}
        memo[id(node)] = entry_map

    return Symbol([memo[id(n)][i] for n, i in sym._outputs])


# --------------------------------------------------------------------------
# Built-in backends
# --------------------------------------------------------------------------

@register_backend("inference")
class _InferencePass(SubgraphProperty):
    """Strip train-only ops for deployment graphs: Dropout becomes a
    pass-through, ``identity``/zero-arg ``Cast``-to-same disappear
    (reference: the quantization/TensorRT properties do the same strip
    before handing subgraphs to the backend)."""

    _DROP = {"Dropout", "identity", "BlockGrad", "stop_gradient"}

    def apply(self, sym, **kwargs):
        def node_fn(node, new_inputs):
            opname = node.op.name if node.op is not None else ""
            if opname in self._DROP and len(new_inputs) == 1:
                return new_inputs[0]
            return None

        return rewrite_nodes(sym, node_fn)
