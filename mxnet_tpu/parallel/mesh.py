"""Device-mesh construction (axes: dp / tp / sp)."""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from ..base import MXNetError

__all__ = ["make_mesh", "mesh_axis_size"]


def make_mesh(dp=None, tp=1, sp=1, devices=None) -> Mesh:
    """Build a Mesh with axes (dp, tp, sp).

    ``dp=None`` absorbs the remaining devices.  On real hardware prefer
    tp/sp on the innermost axes so their collectives ride ICI neighbors
    (jax device order is torus-contiguous).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None:
        if n % (tp * sp):
            raise MXNetError(f"{n} devices not divisible by tp*sp="
                             f"{tp * sp}")
        dp = n // (tp * sp)
    if dp * tp * sp > n:
        raise MXNetError(f"mesh {dp}x{tp}x{sp} needs {dp * tp * sp} "
                         f"devices, only {n} available")
    devices = devices[:dp * tp * sp]  # explicit dims may use a subset
    arr = np.array(devices).reshape(dp, tp, sp)
    return Mesh(arr, axis_names=("dp", "tp", "sp"))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]
