"""Device-mesh construction (axes: dp / tp / sp / ep)."""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from ..base import MXNetError

__all__ = ["make_mesh", "mesh_axis_size"]


def make_mesh(dp=None, tp=1, sp=1, ep=1, devices=None) -> Mesh:
    """Build a Mesh with axes (dp, tp, sp, ep).

    ``dp=None`` absorbs the remaining devices.  ``ep`` is the
    expert-parallel axis (MoE experts sharded across it; unused axes of
    size 1 cost nothing).  On real hardware prefer tp/sp on the
    innermost axes so their collectives ride ICI neighbors (jax device
    order is torus-contiguous).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None:
        if n % (tp * sp * ep):
            raise MXNetError(f"{n} devices not divisible by tp*sp*ep="
                             f"{tp * sp * ep}")
        dp = n // (tp * sp * ep)
    if dp * tp * sp * ep > n:
        raise MXNetError(f"mesh {dp}x{tp}x{sp}x{ep} needs "
                         f"{dp * tp * sp * ep} devices, only {n} available")
    devices = devices[:dp * tp * sp * ep]  # explicit dims may use a subset
    arr = np.array(devices).reshape(dp, tp, sp, ep)
    return Mesh(arr, axis_names=("dp", "tp", "sp", "ep"))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]
