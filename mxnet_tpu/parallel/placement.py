"""Replica placement over the device mesh (docs/serving.md §10).

The serving replica layer (``mxnet_tpu.serving.replica``) maps one
model version to N data-parallel replicas, each owning a **disjoint
device group** of the mesh — a replica is the unit of both throughput
(replicas serve concurrently) and availability (a dead replica's group
takes nothing else down with it).  A replica's group may itself be a
sub-mesh (``tp`` > 1) when the model is tensor-sharded *within* the
replica — the "TensorFlow: A system for large-scale machine learning"
production shape (PAPERS.md): replicate across groups, shard within
one.

These helpers are pure list/shape math over whatever ``jax.devices()``
returns (or any explicit device list — tests pass plain objects), so
placement policy is decided and testable without touching a backend:

- :func:`replica_groups` — split a device list into N disjoint,
  contiguous groups of ``tp`` devices each (contiguous indices ride
  ICI neighbors on real toruses, mirroring ``make_mesh``'s axis-order
  advice).  With fewer devices than replicas ask for,
  ``oversubscribe=True`` shares devices round-robin — the CPU/test
  topology, where replicas are logical (scheduling + failure-isolation
  units) rather than physical.
- :func:`replica_mesh` — a per-replica (dp=1, tp) sub-``Mesh`` over
  one group, for tensor-sharded execution inside the replica.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["replica_groups", "replica_mesh"]


def replica_groups(n_replicas, devices=None, tp=1, oversubscribe=None):
    """Split ``devices`` into ``n_replicas`` disjoint groups of ``tp``.

    Returns a list of ``n_replicas`` tuples of devices.  ``devices``
    defaults to ``jax.devices()``.  Groups are contiguous slices of
    the device order (torus-neighbor-friendly) and strictly disjoint
    when the device count covers ``n_replicas * tp``.

    ``oversubscribe`` controls the under-provisioned case (fewer than
    ``n_replicas * tp`` devices): ``True`` assigns groups round-robin
    so several logical replicas share physical devices; ``False``
    raises; ``None`` (default) oversubscribes only when the whole pool
    is a single device — the CPU test topology — and raises otherwise,
    so a real mesh never silently loses replica fault isolation.
    """
    n_replicas = int(n_replicas)
    tp = int(tp)
    if n_replicas < 1:
        raise MXNetError(
            f"replica_groups: n_replicas must be >= 1, got {n_replicas}")
    if tp < 1:
        raise MXNetError(f"replica_groups: tp must be >= 1, got {tp}")
    if devices is None:
        import jax
        devices = jax.devices()
    devices = list(devices)
    need = n_replicas * tp
    if len(devices) < need:
        if oversubscribe is None:
            oversubscribe = len(devices) == 1
        if not oversubscribe:
            raise MXNetError(
                f"replica_groups: {n_replicas} replica(s) x tp={tp} "
                f"need {need} devices, only {len(devices)} available — "
                f"shrink the replica count, or pass oversubscribe=True "
                f"to share devices (logical replicas lose physical "
                f"fault isolation)")
        return [tuple(devices[(r * tp + i) % len(devices)]
                      for i in range(tp))
                for r in range(n_replicas)]
    return [tuple(devices[r * tp:(r + 1) * tp])
            for r in range(n_replicas)]


def replica_mesh(group, axis_name="tp"):
    """A (1, tp) sub-``Mesh`` over ONE replica's device group, axes
    ``("dp", axis_name)`` — the mesh a tensor-sharded model executes
    against *inside* its replica.  Sharding rules written for the
    training mesh's ``tp`` axis apply unchanged."""
    import numpy as np
    from jax.sharding import Mesh
    group = tuple(group)
    if not group:
        raise MXNetError("replica_mesh: empty device group")
    arr = np.array(group, dtype=object).reshape(1, len(group))
    return Mesh(arr, axis_names=("dp", axis_name))
