"""Sharding rules: parameter-name regex -> PartitionSpec.

Supersedes the reference's manual model parallelism (``ctx_group`` +
``Bind(group2ctx=...)``, SURVEY.md §2.4 P7): instead of placing subgraphs
on devices by hand, parameters carry PartitionSpecs and GSPMD inserts the
collectives.  MEGATRON_RULES cover the in-tree transformer blocks
(column-parallel qkv/ffn_1, row-parallel out_proj/ffn_2).
"""
from __future__ import annotations

import re

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ShardingRules", "MEGATRON_RULES", "partition_params",
           "global_device_put"]


def global_device_put(value, sharding):
    """``jax.device_put`` that also works when ``sharding`` spans
    devices this process cannot address (a multi-process global mesh).

    Plain ``device_put`` of a host value onto a non-addressable
    sharding lowers to cross-host transfer collectives, which the gloo
    CPU transport aborts with a mismatched-size ``EnforceNotMet``
    (the tests/test_dist two-process SPMD failure).  In the SPMD
    program model every process already holds the same host value, so
    the local shards can be sliced out directly and assembled with
    ``make_array_from_callback`` — zero wire traffic, and the only
    path jax guarantees for building global arrays from host data.
    """
    if getattr(value, "sharding", None) == sharding:
        return value
    devices = getattr(sharding, "device_set", None)
    if devices is None \
            or all(d.process_index == jax.process_index()
                   for d in devices):
        return jax.device_put(value, sharding)
    import numpy as np
    host = np.asarray(value)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


class ShardingRules:
    """Ordered (regex, PartitionSpec) table; first match wins."""

    def __init__(self, rules, default=P()):
        self._rules = [(re.compile(pat), spec) for pat, spec in rules]
        self._default = default

    def spec_for(self, name, shape=None):
        for prog, spec in self._rules:
            if prog.search(name):
                if shape is not None and spec != P():
                    # drop specs that don't divide the dims (tiny configs)
                    return spec
                return spec
        return self._default

    def shardings(self, mesh: Mesh, params: dict):
        return {n: NamedSharding(mesh, self._safe_spec(mesh, n, a.shape))
                for n, a in params.items()}

    def _safe_spec(self, mesh, name, shape):
        spec = self.spec_for(name, shape)
        out = []
        for i, axis in enumerate(spec):
            if axis is None or i >= len(shape):
                out.append(None)
                continue
            # axes the mesh doesn't have (e.g. 'ep' on a 3-axis mesh)
            # degrade to replication, same as non-dividing dims
            size = mesh.shape.get(axis, 0) if isinstance(axis, str) else 1
            out.append(axis if size and shape[i] % size == 0 else None)
        return P(*out)


# Megatron-style tensor parallelism for the in-tree transformer layers.
# Dense weights are (out_units, in_units): column-parallel shards dim 0,
# row-parallel shards dim 1.
MEGATRON_RULES = ShardingRules([
    (r"qkv_weight$", P("tp", None)),
    (r"qkv_bias$", P("tp")),
    (r"(q|kv)_proj_weight$", P("tp", None)),
    (r"(q|kv)_proj_bias$", P("tp")),
    (r"out_proj_weight$", P(None, "tp")),
    (r"ffn_1_weight$", P("tp", None)),
    (r"ffn_1_bias$", P("tp")),
    (r"ffn_2_weight$", P(None, "tp")),
    (r"(word_embed|tgt_embed|src_embed).*weight$", P(None, "tp")),
    (r"mlm_decoder_weight$", P("tp", None)),
    (r"mlm_decoder_bias$", P("tp")),
    # MoE experts: dim 0 is the expert dim, sharded over the ep axis;
    # the hidden dim additionally takes tp (GShard layout)
    (r"expert_w1$", P("ep", None, "tp")),
    (r"expert_b1$", P("ep", "tp")),
    (r"expert_w2$", P("ep", "tp", None)),
    (r"expert_b2$", P("ep", None)),
], default=P())


def partition_params(params, mesh, rules=MEGATRON_RULES):
    """Device-put a params dict with rule-derived NamedShardings."""
    shardings = rules.shardings(mesh, params)
    return {n: global_device_put(a, shardings[n])
            for n, a in params.items()}, shardings
