"""Multi-process runtime: process-group bootstrap + DCN-tier collectives.

Reference surface: the dmlc tracker (``tools/launch.py``,
``dmlc_tracker/local.py``) + ``KVStoreDist``'s worker bootstrap
(``DMLC_PS_ROOT_URI``/``DMLC_NUM_WORKER`` env protocol) — SURVEY.md §2.4
P3, §4 "multi-node testing".

TPU-native redesign: the parameter-server control plane is replaced by
JAX's coordination service — ``jax.distributed.initialize`` elects process
0 as coordinator, after which *all* collectives (ICI within a slice, DCN
across slices/hosts) are XLA collectives over the global device set; there
is no separate server role.  On CPU test rigs the same code path runs over
gloo TCP collectives, which is how the multi-process tests execute without
TPU hardware (conftest philosophy: real runtime, fake scale).

Env protocol (reference-compatible names accepted):
  MXNET_TPU_COORDINATOR | DMLC_PS_ROOT_URI[:DMLC_PS_ROOT_PORT]
  MXNET_TPU_NUM_PROCS   | DMLC_NUM_WORKER
  MXNET_TPU_PROC_ID     | DMLC_WORKER_ID
"""
from __future__ import annotations

import atexit
import os
import threading
import time
from typing import Optional

from ..base import MXNetError
from .. import engine as _engine

__all__ = ["initialize", "finalize", "is_initialized", "rank", "size",
           "barrier", "allreduce_host", "broadcast_host", "Watchdog"]

# _state is threading-reachable (atexit finalize vs. watchdog vs. user
# threads); mutate only under _STATE_LOCK.  "finalizing" claims the
# teardown without dropping "initialized" early: is_initialized() stays
# true (and re-initialize stays a no-op) until the shutdown completes.
_state = {"initialized": False, "finalizing": False}
_STATE_LOCK = threading.Lock()


def _env(*names, default=None):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return v
    return default


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               timeout_s: int = 60):
    """Join the process group (reference: KVStoreDist worker bootstrap).

    With no arguments, configuration is read from the env protocol above —
    what ``tools/launch.py`` sets for each spawned worker.  Single-process
    use (no env, no args) is a no-op so scripts run unchanged standalone.
    """
    import jax
    # whole check-and-init under the lock: two racing initialize()
    # calls must not both reach jax.distributed.initialize (the second
    # raises on double client init); the loser blocks, then no-ops
    with _STATE_LOCK:
        did_init = _initialize_locked(jax, coordinator_address,
                                      num_processes, process_id,
                                      timeout_s)
    if did_init:
        atexit.register(finalize)


def _initialize_locked(jax, coordinator_address, num_processes,
                       process_id, timeout_s):
    if _state["initialized"] or _state["finalizing"]:
        return False
    coordinator_address = coordinator_address or _env(
        "MXNET_TPU_COORDINATOR")
    if coordinator_address is None:
        uri = _env("DMLC_PS_ROOT_URI")
        if uri is not None:
            coordinator_address = \
                f"{uri}:{_env('DMLC_PS_ROOT_PORT', default='9091')}"
    if num_processes is None:
        v = _env("MXNET_TPU_NUM_PROCS", "DMLC_NUM_WORKER")
        num_processes = int(v) if v is not None else None
    if process_id is None:
        v = _env("MXNET_TPU_PROC_ID", "DMLC_WORKER_ID")
        process_id = int(v) if v is not None else None
    if coordinator_address is None and num_processes is None:
        return False  # standalone run
    if None in (coordinator_address, num_processes, process_id):
        raise MXNetError(
            "dist.initialize: coordinator_address, num_processes and "
            "process_id must all be provided (or none, for standalone)")
    # DCN-tier collectives over gloo TCP when the CPU client is used
    # (test rigs).  Must not probe the backend here — that would
    # initialize XLA before jax.distributed.initialize.  Harmless on TPU:
    # the flag only affects CPU-client creation.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    kwargs = dict(num_processes=int(num_processes),
                  process_id=int(process_id),
                  initialization_timeout=timeout_s)
    # a crashing worker must EXIT, not block in the shutdown barrier —
    # the launcher's failure detection relies on seeing the exit code
    # promptly (§5.3 clean abort); older jax clients predate the knob
    import inspect
    try:
        sig = inspect.signature(jax.distributed.initialize)
        if "shutdown_timeout_seconds" in sig.parameters:
            kwargs["shutdown_timeout_seconds"] = 15
    except (TypeError, ValueError):     # builtins without a signature
        pass
    jax.distributed.initialize(coordinator_address, **kwargs)
    # mxlint: disable=lock-discipline (contract: sole caller is
    # initialize(), which holds _STATE_LOCK around this helper)
    _state["initialized"] = True
    return True


def finalize():
    # atomically claim the teardown: a concurrent finalize (atexit vs.
    # user thread) sees finalizing=True and returns; initialized is NOT
    # dropped yet — a concurrent initialize() mid-teardown must no-op,
    # not re-create the jax client while shutdown is in flight
    with _STATE_LOCK:
        if not _state["initialized"] or _state["finalizing"]:
            return
        _state["finalizing"] = True
    import jax
    # The shutdown barrier can block forever when a peer is gone (the
    # crash path this atexit hook runs on).  Newer jax clients bound it
    # via shutdown_timeout_seconds at initialize(); older ones lack the
    # knob, so enforce the same 15s clean-abort budget here: run the
    # barrier in a daemon thread and abandon it on timeout.  The process
    # then exits with its ORIGINAL code (a crashed worker's rc reaches
    # the launcher's failure detection, §5.3; a healthy-but-slow
    # shutdown is abandoned, not turned into a failure).

    def _shutdown():
        try:
            jax.distributed.shutdown()
        except Exception:   # noqa: BLE001 — peers may already be gone
            pass

    t = _engine.make_thread(_shutdown, name="mxnet-dist-shutdown",
                            owner="dist.finalize")
    t.start()
    t.join(15)
    if t.is_alive():
        # a peer that never answers wedges jax.distributed.shutdown();
        # the launcher owns the process from here
        _engine.forget_thread(t, "jax.distributed.shutdown() wedged >15s")
    with _STATE_LOCK:
        _state["initialized"] = False
        _state["finalizing"] = False


def is_initialized() -> bool:
    return _state["initialized"]


def rank() -> int:
    import jax
    return jax.process_index()


def size() -> int:
    import jax
    return jax.process_count()


def barrier(name: str = "barrier", timeout_s: int = 120):
    """Cross-process sync point (reference: ps Barrier)."""
    if not _state["initialized"]:
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


def allreduce_host(arr):
    """Sum an array across processes (DCN tier; host-mediated).

    For hot-loop gradients use the sharded-mesh path (parallel/trainer,
    kvstore 'dist_sync') — this helper is for control-plane values
    (metrics, loss scalars, early-stop votes)."""
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from ..ndarray import NDArray
    x = arr._data if isinstance(arr, NDArray) else jnp.asarray(arr)
    if not _state["initialized"]:
        return NDArray(x)
    gathered = multihost_utils.process_allgather(x)
    return NDArray(jnp.sum(gathered, axis=0))


def broadcast_host(arr, root: int = 0):
    """Broadcast from `root` to every process (control-plane values)."""
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from ..ndarray import NDArray
    x = arr._data if isinstance(arr, NDArray) else jnp.asarray(arr)
    if not _state["initialized"]:
        return NDArray(x)
    gathered = multihost_utils.process_allgather(x)
    return NDArray(gathered[root])


class Watchdog:
    """Hang detector: clean abort when a step stops making progress.

    Reference behavior being re-created (SURVEY.md §5.3): the reference's
    ps-lite heartbeats let the tracker detect dead workers and abort the
    job instead of hanging in a collective forever.  Here each process
    runs a watchdog thread; if ``kick()`` is not called within
    ``timeout_s`` the process logs state and hard-exits non-zero, which
    the launcher (tools/launch.py) observes to tear down the whole job.

    Use::

        wd = dist.Watchdog(timeout_s=300); wd.start()
        for batch in data:
            train_step(batch)
            wd.kick()
        wd.stop()
    """

    def __init__(self, timeout_s: float = 300.0, name: str = "step"):
        self.timeout_s = float(timeout_s)
        self.name = name
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread = None

    def kick(self):
        self._last = time.monotonic()

    def start(self):
        if self._thread is not None:
            return self

        def watch():
            while not self._stop.wait(min(self.timeout_s / 4, 10.0)):
                stalled = time.monotonic() - self._last
                if stalled > self.timeout_s:
                    import logging
                    logging.error(
                        "Watchdog %r: no progress for %.0fs (limit %.0fs) "
                        "— aborting process %d so the launcher can tear "
                        "down the job", self.name, stalled, self.timeout_s,
                        rank() if _state["initialized"] else 0)
                    os._exit(42)

        self._thread = _engine.make_thread(
            watch, name=f"watchdog-{self.name}",
            owner=f"dist.Watchdog({self.name})")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
