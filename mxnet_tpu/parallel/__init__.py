"""Parallelism over TPU meshes (SURVEY.md §2.4: P1-P8 + new TP/SP).

The reference scaled via kvstore tiers (local reduce / NCCL / ps-lite —
SURVEY.md §5.8); the TPU-native design scales via ONE mechanism: shard
annotations over a ``jax.sharding.Mesh`` compiled by GSPMD, with XLA
inserting the ICI/DCN collectives.  This package supplies:

- mesh construction (``make_mesh``) with named axes dp/tp/sp/ep (ep =
  expert parallelism for MoE, ops/moe.py + gluon.contrib.MoEFFN);
- ``functionalize``: trace a Gluon Block into a pure fn of
  (params, inputs) — the bridge from the imperative API to pjit;
- sharding rules (regex -> PartitionSpec) with Megatron-style defaults
  for the in-tree transformer blocks;
- pure pytree optimizers (sgd/adamw/lamb) for inside compiled steps;
- ``ShardedTrainer``: one compiled train step = fwd + bwd + update with
  dp/tp shardings (replaces Trainer+kvstore at pod scale);
- ring attention (context parallelism over the ICI ring via ppermute);
- the self-healing layer (docs/training_resilience.md): step watchdog
  (``TrainStepTimeoutError`` instead of a wedged-collective hang),
  ``CheckpointManager`` with verified-marker + integrity-manifest
  restore fallback, and ``TrainingSupervisor`` — bounded restarts
  that resume bit-exactly (RNG + data-cursor checkpointing).

Annotating for SPMD (checked statically by mxlint's mxshard passes —
docs/static_analysis.md, passes 17-19): build meshes with *literal*
axis names and, where possible, literal extents, so every
``PartitionSpec`` checks against the real axis set and dim
divisibility; treat an ``out_specs`` entry of ``P()`` as a *claim*
that every return path reduced the value (``psum``/``pmean``/...) —
``shard_map_unchecked`` (_jax_compat) disables the runtime replication
check, so the static one is the only net; and donate
(``donate_argnums``) only buffers that flow to a matching output, then
rebind the host name in the same statement (``params = step(params)``)
— the old buffer is dead.
"""
from .mesh import make_mesh, mesh_axis_size
from .placement import replica_groups, replica_mesh
from .functional import functionalize
from .sharding import ShardingRules, MEGATRON_RULES, partition_params
from .optim import sgd_init, sgd_update, adamw_init, adamw_update
from .trainer import ShardedTrainer
from .supervisor import TrainingSupervisor, TrainStepTimeoutError, \
    CrashLoopError, StepWatchdog, run_with_deadline
from .ring_attention import ring_attention, ring_self_attention
from .checkpoint import CheckpointManager, save_checkpoint, \
    load_checkpoint
from .pipeline import pipeline_apply, make_pipeline_mesh
from . import dist

__all__ = ["make_mesh", "mesh_axis_size", "replica_groups",
           "replica_mesh", "functionalize",
           "ShardingRules", "MEGATRON_RULES", "partition_params",
           "sgd_init", "sgd_update", "adamw_init", "adamw_update",
           "ShardedTrainer", "TrainingSupervisor",
           "TrainStepTimeoutError", "CrashLoopError", "StepWatchdog",
           "run_with_deadline",
           "ring_attention", "ring_self_attention",
           "CheckpointManager", "save_checkpoint", "load_checkpoint",
           "pipeline_apply", "make_pipeline_mesh",
           "dist"]
