"""Sharded, async checkpointing for compiled training (Orbax/TensorStore).

Reference surface: checkpoint/resume (SURVEY.md §5.4) — upstream's four
user surfaces persist host-side NDArrays (`save_parameters`,
`Module.save_checkpoint`, `Trainer.save_states`), which this build keeps
for the imperative API.  At pod scale those would funnel every shard
through one host; the §5.4 mandate ("implement over TensorStore/OCDBT
with sharded async writes") is this module: each host writes only its
own shards, asynchronously, and restore places shards directly onto the
mesh — no gather, no host bottleneck.

    mngr = CheckpointManager(dir, max_to_keep=3)
    mngr.save(step, trainer)               # async sharded write
    mngr.restore(trainer)                  # latest; or restore(t, step=n)
    mngr.wait()                            # barrier before exit

Two crash-safety pieces on top of the async writes:

- **Atomic last-step marker.**  ``save`` is asynchronous, so "the
  newest step directory exists" does NOT mean "that checkpoint is
  durable" — a preemption mid-write leaves a torn step that the
  backend's ``latest_step()`` may still report.  The manager therefore
  keeps its own ``LATEST`` marker file, written via tmp + fsync +
  rename (atomic on POSIX) only AFTER the write barrier confirms
  durability.  ``restore()`` prefers the marker, so a kill mid-save
  restores the last *verified* checkpoint, never the torn one.
- **``save_on_signal``** — a SIGTERM/preemption hook: the cluster
  scheduler's eviction notice triggers one synchronous save + barrier
  + marker commit before the previous handler (or default
  termination) runs, so an evicted job resumes from its final step
  instead of its last periodic checkpoint.
"""
from __future__ import annotations

import logging
import os
import signal as _signal
from typing import Optional

import jax

from ..base import MXNetError

__all__ = ["CheckpointManager", "save_checkpoint", "load_checkpoint"]

_LOG = logging.getLogger("mxnet_tpu")

_MARKER = "LATEST"


def _ocp():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError as e:                       # pragma: no cover
        raise MXNetError(
            "parallel.checkpoint requires orbax-checkpoint") from e


def _trainer_state(trainer):
    return {"params": dict(trainer.params),
            "opt_state": trainer.opt_state}


def _abstract_like(tree):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=a.sharding),
        tree)


class CheckpointManager:
    """Rolling async sharded checkpoints of a ``ShardedTrainer``.

    Writes OCDBT/TensorStore checkpoints where every process stores only
    its local shards; ``restore`` re-creates arrays with the trainer's
    own shardings.  The ``LATEST`` marker (module docstring) makes the
    latest-pointer torn-write-safe; ``save_on_signal`` turns a
    preemption notice into one final durable checkpoint.
    """

    def __init__(self, directory, max_to_keep: int = 3,
                 async_write: bool = True):
        ocp = _ocp()
        self._dir = os.path.abspath(str(directory))
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_write))
        self._pending = []              # steps saved, durability unknown
        self._signal_prev = {}          # signum -> previous handler

    # ----------------------------------------------------------- save/load
    def save(self, step: int, trainer):
        ocp = _ocp()
        step = int(step)
        self._mngr.save(step,
                        args=ocp.args.StandardSave(
                            _trainer_state(trainer)))
        # the marker only advances at the durability barrier (wait/
        # close/signal-save) — an async save is not yet a fact
        self._pending.append(step)

    def restore(self, trainer, step: Optional[int] = None) -> int:
        """Restore ``trainer``'s params/opt_state in place; returns the
        restored step.  ``step=None`` restores the newest VERIFIED
        step: the atomic marker wins over the backend's directory
        listing, so a checkpoint torn by a mid-save kill is never
        auto-restored (address it explicitly via ``step=`` to try)."""
        ocp = _ocp()
        if step is None:
            step = self.latest_step()
            if step is None:
                raise MXNetError(
                    f"no checkpoint found under {self._dir}")
        target = _abstract_like(_trainer_state(trainer))
        restored = self._mngr.restore(
            int(step), args=ocp.args.StandardRestore(target))
        trainer.params = dict(restored["params"])
        trainer.opt_state = restored["opt_state"]
        return int(step)

    def latest_step(self) -> Optional[int]:
        """Newest restorable step: the verified marker when present
        AND still retained (crash-safe), else whatever the backend
        lists.  The fallback matters twice: pre-marker checkpoint
        directories stay restorable, and a marker step that
        ``max_to_keep`` retention already garbage-collected (saves
        landed after the last barrier, then a kill) must not wedge
        restore while newer durable steps exist — in that case the
        backend listing is the best available answer (the pre-marker
        guarantee, no worse than before)."""
        verified = self.latest_verified_step()
        if verified is not None:
            try:
                retained = verified in set(self._mngr.all_steps())
            except Exception:       # noqa: BLE001 — listing best-effort
                retained = True
            if retained:
                return verified
        return self._mngr.latest_step()

    def all_steps(self):
        return sorted(self._mngr.all_steps())

    # --------------------------------------------------- the atomic marker
    @property
    def _marker_path(self):
        return os.path.join(self._dir, _MARKER)

    def latest_verified_step(self) -> Optional[int]:
        """The step the marker points at — i.e. the newest checkpoint
        PROVEN durable by a completed write barrier — or None (no
        marker yet: nothing verified, or pre-marker directory)."""
        try:
            with open(self._marker_path) as f:
                text = f.read().strip()
            return int(text) if text else None
        except (OSError, ValueError):
            return None

    def _commit_marker(self, step):
        """Atomically repoint the marker: write a tmp file, fsync it,
        rename over the marker.  A kill at ANY instant leaves either
        the old marker or the new one — never a torn pointer."""
        tmp = self._marker_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{int(step)}\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._marker_path)

    def wait(self):
        """Block until pending async writes are durable, then advance
        the verified-latest marker to the newest of them."""
        self._mngr.wait_until_finished()
        if self._pending:
            self._commit_marker(max(self._pending))
            self._pending = []

    def close(self):
        self.wait()
        self._mngr.close()

    # ------------------------------------------------------ signal handling
    def save_on_signal(self, trainer, step_fn,
                       signals=(_signal.SIGTERM,)):
        """Install a preemption hook: on any of ``signals`` (default
        SIGTERM — what cluster schedulers send before eviction), run
        ONE synchronous save of ``trainer`` at ``step_fn()`` —
        save, write barrier, marker commit — then chain to the
        previously installed handler (or the default action), so the
        process still terminates the way its supervisor expects.

        ``step_fn`` is a zero-arg callable returning the step to stamp
        (e.g. ``lambda: trainer_loop.step``); it is evaluated at
        signal time, not install time.  Returns this manager so the
        call chains.  Must run on the main thread (CPython signal
        rule).  ``remove_signal_handlers()`` undoes the install."""
        if not callable(step_fn):
            raise MXNetError(
                "save_on_signal: step_fn must be a zero-arg callable "
                "returning the step to save at signal time")

        def handler(signum, frame):
            try:
                step = int(step_fn())
                _LOG.warning(
                    "checkpoint: signal %s — saving final checkpoint "
                    "at step %d to %s", signum, step, self._dir)
                self.save(step, trainer)
                self.wait()             # barrier + marker commit
            except Exception as e:      # noqa: BLE001 — still terminate
                _LOG.error(
                    "checkpoint: signal-save failed (%s); the last "
                    "verified checkpoint is step %s", e,
                    self.latest_verified_step())
            prev = self._signal_prev.get(signum)
            if callable(prev):
                prev(signum, frame)
            elif prev != _signal.SIG_IGN:
                # SIG_DFL — or None, i.e. a handler installed at the C
                # level that Python cannot re-invoke: re-raise with the
                # default action so the process still terminates and
                # the exit status reflects the signal (supervisors key
                # on it); swallowing it would leave a zombie the
                # supervisor has to SIGKILL
                _signal.signal(signum, _signal.SIG_DFL)
                _signal.raise_signal(signum)

        for signum in signals:
            self._signal_prev[signum] = _signal.signal(signum, handler)
        return self

    def remove_signal_handlers(self):
        """Restore the handlers ``save_on_signal`` displaced."""
        for signum, prev in self._signal_prev.items():
            _signal.signal(signum, prev)
        self._signal_prev = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.remove_signal_handlers()
        self.close()


def save_checkpoint(directory, trainer, step: int = 0):
    """One-shot synchronous sharded save (no retention policy)."""
    with CheckpointManager(directory, max_to_keep=None,
                           async_write=False) as m:
        m.save(step, trainer)


def load_checkpoint(directory, trainer, step: Optional[int] = None) -> int:
    """Restore the latest (or ``step``) checkpoint into ``trainer``."""
    with CheckpointManager(directory) as m:
        return m.restore(trainer, step=step)
