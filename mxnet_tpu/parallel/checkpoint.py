"""Sharded, async checkpointing for compiled training (Orbax/TensorStore).

Reference surface: checkpoint/resume (SURVEY.md §5.4) — upstream's four
user surfaces persist host-side NDArrays (`save_parameters`,
`Module.save_checkpoint`, `Trainer.save_states`), which this build keeps
for the imperative API.  At pod scale those would funnel every shard
through one host; the §5.4 mandate ("implement over TensorStore/OCDBT
with sharded async writes") is this module: each host writes only its
own shards, asynchronously, and restore places shards directly onto the
mesh — no gather, no host bottleneck.

    mngr = CheckpointManager(dir, max_to_keep=3)
    mngr.save(step, trainer)               # async sharded write
    mngr.restore(trainer)                  # latest; or restore(t, step=n)
    mngr.wait()                            # barrier before exit
"""
from __future__ import annotations

import os
from typing import Optional

import jax

from ..base import MXNetError

__all__ = ["CheckpointManager", "save_checkpoint", "load_checkpoint"]


def _ocp():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError as e:                       # pragma: no cover
        raise MXNetError(
            "parallel.checkpoint requires orbax-checkpoint") from e


def _trainer_state(trainer):
    return {"params": dict(trainer.params),
            "opt_state": trainer.opt_state}


def _abstract_like(tree):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=a.sharding),
        tree)


class CheckpointManager:
    """Rolling async sharded checkpoints of a ``ShardedTrainer``.

    Writes OCDBT/TensorStore checkpoints where every process stores only
    its local shards; ``restore`` re-creates arrays with the trainer's
    own shardings.
    """

    def __init__(self, directory, max_to_keep: int = 3,
                 async_write: bool = True):
        ocp = _ocp()
        self._dir = os.path.abspath(str(directory))
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_write))

    def save(self, step: int, trainer):
        ocp = _ocp()
        self._mngr.save(int(step),
                        args=ocp.args.StandardSave(
                            _trainer_state(trainer)))

    def restore(self, trainer, step: Optional[int] = None) -> int:
        """Restore ``trainer``'s params/opt_state in place; returns the
        restored step."""
        ocp = _ocp()
        if step is None:
            step = self.latest_step()
            if step is None:
                raise MXNetError(
                    f"no checkpoint found under {self._dir}")
        target = _abstract_like(_trainer_state(trainer))
        restored = self._mngr.restore(
            int(step), args=ocp.args.StandardRestore(target))
        trainer.params = dict(restored["params"])
        trainer.opt_state = restored["opt_state"]
        return int(step)

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self):
        return sorted(self._mngr.all_steps())

    def wait(self):
        """Block until pending async writes are durable."""
        self._mngr.wait_until_finished()

    def close(self):
        self.wait()
        self._mngr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_checkpoint(directory, trainer, step: int = 0):
    """One-shot synchronous sharded save (no retention policy)."""
    with CheckpointManager(directory, max_to_keep=None,
                           async_write=False) as m:
        m.save(step, trainer)


def load_checkpoint(directory, trainer, step: Optional[int] = None) -> int:
    """Restore the latest (or ``step``) checkpoint into ``trainer``."""
    with CheckpointManager(directory) as m:
        return m.restore(trainer, step=step)
