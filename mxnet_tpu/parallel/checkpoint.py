"""Sharded, async checkpointing for compiled training (Orbax/TensorStore).

Reference surface: checkpoint/resume (SURVEY.md §5.4) — upstream's four
user surfaces persist host-side NDArrays (`save_parameters`,
`Module.save_checkpoint`, `Trainer.save_states`), which this build keeps
for the imperative API.  At pod scale those would funnel every shard
through one host; the §5.4 mandate ("implement over TensorStore/OCDBT
with sharded async writes") is this module: each host writes only its
own shards, asynchronously, and restore places shards directly onto the
mesh — no gather, no host bottleneck.

    mngr = CheckpointManager(dir, max_to_keep=3)
    mngr.save(step, trainer)               # async sharded write
    mngr.restore(trainer)                  # latest; or restore(t, step=n)
    mngr.wait()                            # barrier before exit

Crash-safety pieces on top of the async writes:

- **Atomic last-step marker.**  ``save`` is asynchronous, so "the
  newest step directory exists" does NOT mean "that checkpoint is
  durable" — a preemption mid-write leaves a torn step that the
  backend's ``latest_step()`` may still report.  The manager therefore
  keeps its own ``LATEST`` marker file, written via tmp + fsync +
  rename (atomic on POSIX) only AFTER the write barrier confirms
  durability.  ``restore()`` prefers the marker, so a kill mid-save
  restores the last *verified* checkpoint, never the torn one.
- **Per-step integrity manifest.**  The marker says a barrier
  completed; it cannot say the bytes are still good.  At each barrier
  the manager also records a ``VERIFY-<step>.json`` manifest (relative
  path -> sha256 over the step directory), and auto-``restore()``
  re-hashes against it first: a bit-flipped or torn payload at the
  marker step is DETECTED and restore **falls back to the previous
  verified step with a warning** instead of raising or silently
  loading rot — symmetric with the retention-GC fallback in
  ``latest_step()``.  An explicitly requested ``step=`` skips the
  fallback (you asked for those bytes; you get the error).
- **Extra payload.**  ``save(step, trainer, extra=...)`` persists a
  small JSON side-state (``EXTRA-<step>.json``, atomic write at the
  barrier) next to the array tree — the supervisor stores the eager
  RNG snapshot, the data-iterator cursor, and the loss trajectory
  there, which is what makes resume bit-exact rather than merely
  weight-correct.  ``load_extra(step)`` reads it back.
- **``save_on_signal``** — a SIGTERM/preemption hook: the cluster
  scheduler's eviction notice triggers one synchronous save + barrier
  + marker commit before the previous handler (or default
  termination) runs, so an evicted job resumes from its final step
  instead of its last periodic checkpoint.

Fault-injection sites (``mxnet_tpu.faults``): ``checkpoint.save``
(fail/delay/stall at save; **corrupt** fires at the barrier and
bit-flips one payload byte of the just-verified step — the
silent-rot/torn-write shape the manifest exists to catch) and
``checkpoint.restore`` (fail/delay/stall at restore; **corrupt**
bit-flips the candidate step's payload before reading, which the
manifest check must turn into a fallback, never wrong weights).
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import signal as _signal
import time
from typing import Optional

import jax

from .. import faults as _faults
from ..base import MXNetError

__all__ = ["CheckpointManager", "save_checkpoint", "load_checkpoint"]

_LOG = logging.getLogger("mxnet_tpu")

_MARKER = "LATEST"


def _ocp():
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError as e:                       # pragma: no cover
        raise MXNetError(
            "parallel.checkpoint requires orbax-checkpoint") from e


def _trainer_state(trainer):
    state = {"params": dict(trainer.params),
             "opt_state": trainer.opt_state}
    # quantized-collective error-feedback residuals are step state: a
    # resume without them diverges from the uninterrupted trajectory
    residuals = getattr(trainer, "residuals", None)
    if residuals:
        state["residuals"] = dict(residuals)
    return state


def _abstract_like(tree):
    # sharding is optional so numpy-fake trainers (tests, supervisor
    # unit coverage) round-trip without a device mesh
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=getattr(a, "sharding",
                                                        None)),
        tree)


def _inject(site, modes):
    """Checkpoint-site fault hook.  fail raises, delay/stall sleep;
    a fired ``corrupt`` rule is RETURNED for the caller to apply to
    real bytes on disk (this is the torn/bit-flipped-payload site —
    nothing useful flows through the call itself)."""
    plan = _faults.active()
    if plan is None:
        return None
    rule = plan.fire(site, modes=modes)
    if rule is None:
        return None
    if rule.mode == "fail":
        raise _faults.InjectedFault(site)
    if rule.mode in ("delay", "stall"):
        time.sleep(rule.ms / 1e3)
        return None
    return rule                         # corrupt


def _flip_payload_byte(root):
    """Bit-flip one byte of the largest payload file under ``root`` —
    the injected silent-rot / torn-write.  Returns the mutated path
    (or None when the directory holds nothing to corrupt)."""
    victim, size = None, -1
    for dirpath, _dirs, files in os.walk(root):
        for name in files:
            path = os.path.join(dirpath, name)
            try:
                n = os.path.getsize(path)
            except OSError:
                continue
            if n > size:
                victim, size = path, n
    if victim is None or size <= 0:
        return None
    with open(victim, "r+b") as f:
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    return victim


class CheckpointManager:
    """Rolling async sharded checkpoints of a ``ShardedTrainer``.

    Writes OCDBT/TensorStore checkpoints where every process stores only
    its local shards; ``restore`` re-creates arrays with the trainer's
    own shardings.  The ``LATEST`` marker (module docstring) makes the
    latest-pointer torn-write-safe; ``save_on_signal`` turns a
    preemption notice into one final durable checkpoint.
    """

    def __init__(self, directory, max_to_keep: int = 3,
                 async_write: bool = True):
        ocp = _ocp()
        self._dir = os.path.abspath(str(directory))
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_write))
        self._pending = []              # steps saved, durability unknown
        self._pending_extra = {}        # step -> extra payload (JSON)
        self._signal_prev = {}          # signum -> previous handler

    # ----------------------------------------------------------- save/load
    def save(self, step: int, trainer, extra=None):
        """Queue one async sharded save.  ``extra`` (JSON-serializable
        dict: RNG snapshot, iterator cursor, ...) is persisted at the
        durability barrier alongside the step."""
        ocp = _ocp()
        step = int(step)
        _inject("checkpoint.save", modes=("fail", "delay", "stall"))
        self._mngr.save(step,
                        args=ocp.args.StandardSave(
                            _trainer_state(trainer)))
        # the marker only advances at the durability barrier (wait/
        # close/signal-save) — an async save is not yet a fact
        self._pending.append(step)
        if extra is not None:
            self._pending_extra[step] = extra

    def restore(self, trainer, step: Optional[int] = None) -> int:
        """Restore ``trainer``'s state in place; returns the restored
        step.  ``step=None`` walks the newest-verified-first candidate
        list: the atomic marker's step, then older retained steps —
        each integrity-checked against its barrier manifest before any
        bytes are trusted, so a corrupt/torn payload at the marker
        step FALLS BACK to the previous verified step with a warning
        instead of raising (or worse, loading rot).  An explicit
        ``step=`` restores exactly that step and raises on damage."""
        corrupt = _inject("checkpoint.restore",
                          modes=("fail", "delay", "stall", "corrupt"))
        if step is not None:
            if corrupt is not None:
                flipped = _flip_payload_byte(self._step_dir(int(step)))
                _LOG.warning("checkpoint: injected payload corruption "
                             "at step %d (%s)", int(step), flipped)
            return self._restore_exact(trainer, int(step))
        candidates = self._candidate_steps()
        if not candidates:
            raise MXNetError(
                f"no checkpoint found under {self._dir}")
        if corrupt is not None:
            flipped = _flip_payload_byte(self._step_dir(candidates[0]))
            _LOG.warning("checkpoint: injected payload corruption at "
                         "step %d (%s)", candidates[0], flipped)
        verified = self.latest_verified_step()
        # while the marker step is still retained, any NEWER step
        # without a manifest never completed a barrier (kill mid-save)
        # — "no manifest" there means torn, not legacy, and restoring
        # it would also skip its extra payload (RNG/cursor), breaking
        # bit-exact resume.  A STALE marker (its step already
        # retention-GC'd) proves nothing about newer steps, so the
        # legacy best-available fallback applies there.
        marker_retained = verified is not None and verified in candidates
        failures = []
        for cand in candidates:
            require = marker_retained and cand > verified
            ok, why = self._verify_step(cand,
                                        require_manifest=require)
            if not ok:
                _LOG.warning(
                    "checkpoint: step %d payload corrupt/torn (%s) — "
                    "falling back to the previous verified step", cand,
                    why)
                failures.append((cand, why))
                continue
            try:
                return self._restore_exact(trainer, cand)
            except Exception as e:  # noqa: BLE001 — try older steps
                _LOG.warning(
                    "checkpoint: restore of step %d failed (%s) — "
                    "falling back to the previous verified step",
                    cand, e)
                failures.append((cand, repr(e)))
        raise MXNetError(
            f"no restorable checkpoint under {self._dir}: every "
            f"candidate failed verification or restore: {failures}")

    def _restore_exact(self, trainer, step: int) -> int:
        ocp = _ocp()
        target = _abstract_like(_trainer_state(trainer))
        restored = self._mngr.restore(
            int(step), args=ocp.args.StandardRestore(target))
        trainer.params = dict(restored["params"])
        trainer.opt_state = restored["opt_state"]
        if "residuals" in restored and hasattr(trainer, "residuals"):
            trainer.residuals = dict(restored["residuals"])
        return int(step)

    def _candidate_steps(self):
        """Auto-restore order: the verified-marker step first, then
        every other retained step newest-first."""
        steps = sorted(self._mngr.all_steps(), reverse=True)
        verified = self.latest_verified_step()
        if verified is not None and verified in steps:
            steps.remove(verified)
            steps.insert(0, verified)
        return steps

    def latest_step(self) -> Optional[int]:
        """Newest restorable step: the verified marker when present
        AND still retained (crash-safe), else whatever the backend
        lists.  The fallback matters twice: pre-marker checkpoint
        directories stay restorable, and a marker step that
        ``max_to_keep`` retention already garbage-collected (saves
        landed after the last barrier, then a kill) must not wedge
        restore while newer durable steps exist — in that case the
        backend listing is the best available answer (the pre-marker
        guarantee, no worse than before)."""
        verified = self.latest_verified_step()
        if verified is not None:
            try:
                retained = verified in set(self._mngr.all_steps())
            except Exception:       # noqa: BLE001 — listing best-effort
                retained = True
            if retained:
                return verified
        return self._mngr.latest_step()

    def all_steps(self):
        return sorted(self._mngr.all_steps())

    # --------------------------------------------------- the atomic marker
    @property
    def _marker_path(self):
        return os.path.join(self._dir, _MARKER)

    def latest_verified_step(self) -> Optional[int]:
        """The step the marker points at — i.e. the newest checkpoint
        PROVEN durable by a completed write barrier — or None (no
        marker yet: nothing verified, or pre-marker directory)."""
        try:
            with open(self._marker_path) as f:
                text = f.read().strip()
            return int(text) if text else None
        except (OSError, ValueError):
            return None

    def _commit_marker(self, step):
        """Atomically repoint the marker (tmp + fsync + rename): a
        kill at ANY instant leaves either the old marker or the new
        one — never a torn pointer."""
        self._atomic_write(self._marker_path, f"{int(step)}\n")

    # ------------------------------------------- integrity manifest + extra
    def _step_dir(self, step):
        return os.path.join(self._dir, str(int(step)))

    def _manifest_path(self, step):
        return os.path.join(self._dir, f"VERIFY-{int(step)}.json")

    def _extra_path(self, step):
        return os.path.join(self._dir, f"EXTRA-{int(step)}.json")

    @staticmethod
    def _atomic_write(path, text):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _hash_step(self, step):
        """{relative path: sha256} over the step directory."""
        root = self._step_dir(step)
        digests = {}
        for dirpath, _dirs, files in os.walk(root):
            for name in sorted(files):
                path = os.path.join(dirpath, name)
                h = hashlib.sha256()
                try:
                    with open(path, "rb") as f:
                        for chunk in iter(lambda: f.read(1 << 20), b""):
                            h.update(chunk)
                except OSError:
                    continue            # transient tmp file mid-rename
                digests[os.path.relpath(path, root)] = h.hexdigest()
        return digests

    def _write_manifest(self, step):
        self._atomic_write(
            self._manifest_path(step),
            json.dumps({"step": int(step),
                        "files": self._hash_step(step)}))

    def _verify_step(self, step, require_manifest=False):
        """(ok, why) integrity verdict for one step.  Without
        ``require_manifest``, no manifest (a pre-manifest legacy
        directory) counts as ok — the restore itself is then the only
        available check, and its failure still falls back."""
        try:
            with open(self._manifest_path(step)) as f:
                manifest = json.load(f)
        except OSError:
            if require_manifest:
                return False, ("no manifest — the step never "
                               "completed a durability barrier")
            return True, "no manifest (pre-manifest step)"
        except ValueError as e:
            return False, f"manifest unreadable: {e}"
        expect = manifest.get("files", {})
        got = self._hash_step(step)
        if got != expect:
            changed = sorted(
                set(expect) ^ set(got)
                | {p for p in expect
                   if p in got and got[p] != expect[p]})
            return False, f"payload digest mismatch: {changed[:4]}"
        return True, "verified"

    def load_extra(self, step):
        """The ``extra`` payload saved with ``step`` (or None)."""
        try:
            with open(self._extra_path(step)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _gc_sidecars(self):
        """Drop VERIFY-/EXTRA- files for steps the backend's retention
        already garbage-collected."""
        try:
            live = {int(s) for s in self._mngr.all_steps()}
            names = os.listdir(self._dir)
        except Exception:   # noqa: BLE001 — housekeeping, best effort
            return
        for name in names:
            for prefix in ("VERIFY-", "EXTRA-"):
                if name.startswith(prefix) and name.endswith(".json"):
                    try:
                        step = int(name[len(prefix):-len(".json")])
                    except ValueError:
                        continue
                    if step not in live:
                        try:
                            os.remove(os.path.join(self._dir, name))
                        except OSError:
                            pass

    def wait(self):
        """Block until pending async writes are durable, then record
        each pending step's integrity manifest (+ extra payload) and
        advance the verified-latest marker to the newest of them."""
        self._mngr.wait_until_finished()
        if self._pending:
            newest = max(self._pending)
            for step in sorted(set(self._pending)):
                extra = self._pending_extra.pop(step, None)
                if extra is not None:
                    self._atomic_write(self._extra_path(step),
                                       json.dumps(extra))
                self._write_manifest(step)
            self._commit_marker(newest)
            self._pending = []
            self._gc_sidecars()
            # the torn/bit-rot injection site: corrupt AFTER the
            # barrier verified the step, so restore must detect it
            # via the manifest and fall back
            if _inject("checkpoint.save", modes=("corrupt",)) \
                    is not None:
                flipped = _flip_payload_byte(self._step_dir(newest))
                _LOG.warning(
                    "checkpoint: injected payload corruption at "
                    "verified step %d (%s)", newest, flipped)

    def close(self):
        self.wait()
        self._mngr.close()

    # ------------------------------------------------------ signal handling
    def save_on_signal(self, trainer, step_fn,
                       signals=(_signal.SIGTERM,)):
        """Install a preemption hook: on any of ``signals`` (default
        SIGTERM — what cluster schedulers send before eviction), run
        ONE synchronous save of ``trainer`` at ``step_fn()`` —
        save, write barrier, marker commit — then chain to the
        previously installed handler (or the default action), so the
        process still terminates the way its supervisor expects.

        ``step_fn`` is a zero-arg callable returning the step to stamp
        (e.g. ``lambda: trainer_loop.step``); it is evaluated at
        signal time, not install time.  Returns this manager so the
        call chains.  Must run on the main thread (CPython signal
        rule).  ``remove_signal_handlers()`` undoes the install."""
        if not callable(step_fn):
            raise MXNetError(
                "save_on_signal: step_fn must be a zero-arg callable "
                "returning the step to save at signal time")

        def handler(signum, frame):
            try:
                step = int(step_fn())
                _LOG.warning(
                    "checkpoint: signal %s — saving final checkpoint "
                    "at step %d to %s", signum, step, self._dir)
                self.save(step, trainer)
                self.wait()             # barrier + marker commit
            except Exception as e:      # noqa: BLE001 — still terminate
                _LOG.error(
                    "checkpoint: signal-save failed (%s); the last "
                    "verified checkpoint is step %s", e,
                    self.latest_verified_step())
            prev = self._signal_prev.get(signum)
            if callable(prev):
                prev(signum, frame)
            elif prev != _signal.SIG_IGN:
                # SIG_DFL — or None, i.e. a handler installed at the C
                # level that Python cannot re-invoke: re-raise with the
                # default action so the process still terminates and
                # the exit status reflects the signal (supervisors key
                # on it); swallowing it would leave a zombie the
                # supervisor has to SIGKILL
                _signal.signal(signum, _signal.SIG_DFL)
                _signal.raise_signal(signum)

        for signum in signals:
            self._signal_prev[signum] = _signal.signal(signum, handler)
        return self

    def remove_signal_handlers(self):
        """Restore the handlers ``save_on_signal`` displaced."""
        for signum, prev in self._signal_prev.items():
            _signal.signal(signum, prev)
        self._signal_prev = {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.remove_signal_handlers()
        self.close()


def save_checkpoint(directory, trainer, step: int = 0):
    """One-shot synchronous sharded save (no retention policy)."""
    with CheckpointManager(directory, max_to_keep=None,
                           async_write=False) as m:
        m.save(step, trainer)


def load_checkpoint(directory, trainer, step: Optional[int] = None) -> int:
    """Restore the latest (or ``step``) checkpoint into ``trainer``."""
    with CheckpointManager(directory) as m:
        return m.restore(trainer, step=step)
