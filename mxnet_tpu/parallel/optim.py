"""Pure pytree optimizers for compiled train steps.

Same math as the fused ops (ops/optimizer_ops.py) but over whole param
pytrees, so the entire update fuses into the pjit step program and XLA
donates the buffers (the in-place behavior of the reference's fused
optimizer ops at the memory level).

NOTE: update fns use one tree_map per returned tree — a single tree_map
whose fn returns a tuple would NEST the tuple into the pytree (tree_map
treats tuples as subtrees, not leaves).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["sgd_init", "sgd_update", "adamw_init", "adamw_update",
           "lamb_init", "lamb_update"]

_tree_map = jax.tree_util.tree_map


# ------------------------------------------------------------------- SGD
def sgd_init(params):
    return {"mom": _tree_map(jnp.zeros_like, params)}


def sgd_update(params, grads, state, lr=0.01, momentum=0.9, wd=0.0):
    new_m = _tree_map(
        lambda w, g, m: momentum * m - lr * (g + wd * w),
        params, grads, state["mom"])
    new_p = _tree_map(lambda w, m: w + m, params, new_m)
    return new_p, {"mom": new_m}


# ----------------------------------------------------------------- AdamW
def adamw_init(params):
    return {"mean": _tree_map(jnp.zeros_like, params),
            "var": _tree_map(jnp.zeros_like, params),
            "step": jnp.zeros((), jnp.int32)}


def adamw_update(params, grads, state, lr=1e-3, beta1=0.9, beta2=0.999,
                 eps=1e-8, wd=0.01):
    step = state["step"] + 1
    c1 = 1.0 - beta1 ** step.astype(jnp.float32)
    c2 = 1.0 - beta2 ** step.astype(jnp.float32)
    new_m = _tree_map(lambda g, m: beta1 * m + (1 - beta1) * g,
                      grads, state["mean"])
    new_v = _tree_map(lambda g, v: beta2 * v + (1 - beta2) * jnp.square(g),
                      grads, state["var"])
    new_p = _tree_map(
        lambda w, m, v: w - lr * ((m / c1) / (jnp.sqrt(v / c2) + eps)
                                  + wd * w),
        params, new_m, new_v)
    return new_p, {"mean": new_m, "var": new_v, "step": step}


# ------------------------------------------------------------------ LAMB
def lamb_init(params):
    return adamw_init(params)


def lamb_update(params, grads, state, lr=1e-3, beta1=0.9, beta2=0.999,
                eps=1e-6, wd=0.01):
    step = state["step"] + 1
    c1 = 1.0 - beta1 ** step.astype(jnp.float32)
    c2 = 1.0 - beta2 ** step.astype(jnp.float32)
    new_m = _tree_map(lambda g, m: beta1 * m + (1 - beta1) * g,
                      grads, state["mean"])
    new_v = _tree_map(lambda g, v: beta2 * v + (1 - beta2) * jnp.square(g),
                      grads, state["var"])

    def upd(w, m, v):
        u = (m / c1) / (jnp.sqrt(v / c2) + eps) + wd * w
        r1 = jnp.linalg.norm(w.reshape(-1))
        r2 = jnp.linalg.norm(u.reshape(-1))
        ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
        return w - lr * ratio * u

    new_p = _tree_map(upd, params, new_m, new_v)
    return new_p, {"mean": new_m, "var": new_v, "step": step}
