"""Bridge from the imperative Gluon API to pure functions for pjit.

``functionalize(block, *example_inputs)`` returns ``(apply_fn, params)``:
``apply_fn(params_dict, *input_arrays) -> (outputs, aux_updates)`` is a
pure traced re-execution of the block's forward (same mechanism as the
CachedOp, gluon/block.py), so the identical model object drives both the
eager path and pod-scale pjit training.
"""
from __future__ import annotations

from collections import OrderedDict

from .. import autograd
from ..ndarray import NDArray
from ..gluon.block import _AUX_CAPTURE, _TRACING, _flatten
from ..gluon.parameter import _PARAM_OVERRIDE

__all__ = ["functionalize"]


def functionalize(block, *example_inputs, train_mode=True):
    """Returns (apply_fn, init_params).

    apply_fn(params: dict[str, Array], *inputs: Array)
        -> (tuple_of_outputs, dict_of_aux_updates)
    init_params: dict[str, jax.Array] snapshot of current values.
    """
    # resolve deferred shapes with one imperative pass — only when needed
    # (the pass runs op-by-op; for fully-specified models skip it)
    needs_pass = any(p._deferred_init is not None or not p._data
                     for p in block.collect_params().values())
    if needs_pass:
        nd_inputs = [x if isinstance(x, NDArray) else NDArray(x)
                     for x in example_inputs]
        was_active = getattr(block, "_active", False)
        if hasattr(block, "hybridize"):
            block.hybridize(False)
        with autograd.pause(train_mode=train_mode):
            block(*nd_inputs)
        if hasattr(block, "hybridize") and was_active:
            block.hybridize(True)

    params = OrderedDict(block.collect_params().items())
    names = list(params)

    def apply_fn(param_arrays, *input_arrays):
        xs = [NDArray(a) for a in input_arrays]
        override = {params[n]: NDArray(param_arrays[n]) for n in names}
        tok_t = _TRACING.set(True)
        tok_p = _PARAM_OVERRIDE.set(override)
        tok_a = _AUX_CAPTURE.set(OrderedDict())
        try:
            with autograd.pause(train_mode=train_mode):
                out = block.forward(*xs)
            cap = _AUX_CAPTURE.get()
        finally:
            _AUX_CAPTURE.reset(tok_a)
            _PARAM_OVERRIDE.reset(tok_p)
            _TRACING.reset(tok_t)
        flat, tree = _flatten(out)
        aux = {p.name: v for p, v in cap.items()}
        outs = tuple(x._data for x in flat)
        return (outs[0] if tree is None else outs), aux

    init_params = {n: params[n].data()._data for n in names}
    return apply_fn, init_params
