"""ShardedTrainer: ONE compiled train step over a device mesh.

Replaces Trainer+kvstore at pod scale (SURVEY.md §3.4 TPU mapping): the
entire fwd+bwd+optimizer+allreduce is a single pjit program; XLA lowers
the gradient reductions to ICI/DCN collectives from the shardings alone.

With ``compression=`` (int8/fp8, ``mxnet_tpu.quantize``) the
data-parallel gradient mean runs as an EXPLICIT quantized collective
instead: the step computes per-device gradients under ``shard_map``
over the ``dp`` axis, error-feedback-quantizes each device's
contribution, all-gathers only the compressed payload + per-block f32
scales, and dequant-accumulates in f32 — still ONE compiled program
(quant/dequant fuse into the collective), but the bytes crossing chips
shrink ~4x (EQuARX, PAPERS.md).  The per-device rounding-error
residuals ride the donated step state like the optimizer state does.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import faults as _faults
from .. import perf_account as _pa
from .. import quantize as qz
from .. import runtime_metrics as _rm
from .._jax_compat import shard_map_unchecked
from ..base import MXNetError
from . import optim as _optim
from .functional import functionalize
from .sharding import MEGATRON_RULES, global_device_put, partition_params
from .supervisor import StepWatchdog

__all__ = ["ShardedTrainer"]

def _sgd_shardings(ps, repl):
    return {"mom": dict(ps)}


def _adam_shardings(ps, repl):
    return {"mean": dict(ps), "var": dict(ps), "step": repl}


_OPTIMS = {
    "sgd": (_optim.sgd_init, _optim.sgd_update, _sgd_shardings),
    "adamw": (_optim.adamw_init, _optim.adamw_update, _adam_shardings),
    "lamb": (_optim.lamb_init, _optim.lamb_update, _adam_shardings),
}


class ShardedTrainer:
    """Compile a data+tensor-parallel training step for a Gluon block.

    loss_fn(outputs, *labels) -> scalar, written in jnp over raw arrays.
    Batch dims of inputs/labels are sharded over "dp"; params follow
    ``rules`` (default Megatron TP).  Donation gives in-place updates.
    """

    def __init__(self, block, loss_fn, mesh: Mesh, optimizer="adamw",
                 optimizer_params=None, rules=MEGATRON_RULES,
                 example_inputs=(), n_labels=1, dtype=None,
                 compression=None, step_timeout_ms=None,
                 slow_step_factor=None):
        if optimizer not in _OPTIMS:
            raise MXNetError(f"unknown optimizer {optimizer!r}; "
                             f"known: {sorted(_OPTIMS)}")
        self.mesh = mesh
        self.block = block
        # step deadline + straggler detection (defaults from
        # MXNET_TRAIN_STEP_TIMEOUT_MS / MXNET_TRAIN_SLOW_STEP_FACTOR;
        # both off = step() dispatches directly, zero wrapper cost)
        self.watchdog = StepWatchdog(timeout_ms=step_timeout_ms,
                                     slow_factor=slow_step_factor)
        # step-time attribution / MFU / bottleneck verdict — inert
        # (one attribute load + branch in step()) until MXNET_TRACE or
        # MXNET_RUNTIME_METRICS turns it on
        self.perf = _pa.StepAttribution()
        self._flops_noted = False
        self.compression = qz.CompressionSpec.parse(compression)
        if self.compression is not None:
            if "dp" not in mesh.shape:
                raise MXNetError(
                    "ShardedTrainer(compression=...): mesh has no 'dp' "
                    "axis to compress gradients over")
            sharded_axes = [a for a, s in mesh.shape.items()
                            if a != "dp" and s > 1]
            if sharded_axes:
                raise MXNetError(
                    f"ShardedTrainer(compression=...) needs a pure "
                    f"data-parallel mesh: axes {sharded_axes} have size "
                    f"> 1, and quantized sync of tensor/pipeline-"
                    f"sharded gradients is not supported — drop "
                    f"compression or reshape the mesh to dp-only")
        opt_init, opt_update, opt_shard = _OPTIMS[optimizer]
        opt_kw = dict(optimizer_params or {})
        if "learning_rate" in opt_kw:
            opt_kw["lr"] = opt_kw.pop("learning_rate")
        if "weight_decay" in opt_kw:            # Gluon naming → optim's
            opt_kw["wd"] = opt_kw.pop("weight_decay")

        apply_fn, params = functionalize(block, *example_inputs,
                                         train_mode=True)
        # device_put below may ALIAS the Block's live buffers on
        # same-backend transfers; the step donates params, and donating
        # an aliased buffer deletes the imperative API's view (a later
        # wait_to_read/waitall then fails with "deleted or donated
        # buffer").  astype is a no-op alias when the dtype already
        # matches, so copy unconditionally in BOTH branches.
        def _own(a):
            if dtype is not None and jnp.issubdtype(a.dtype, jnp.floating):
                return jnp.array(a, dtype=dtype, copy=True)
            return jnp.array(a, copy=True)

        params = {n: _own(a) for n, a in params.items()}
        self.params, self.param_shardings = partition_params(
            params, mesh, rules)
        self.opt_state = opt_init(self.params)
        self._n_inputs = len(example_inputs)
        self._n_labels = int(n_labels)
        # aux/frozen params (grad_req='null': BatchNorm running stats,
        # positional constants) must NOT receive optimizer updates — with
        # zero grads the weight-decay term would silently erode them
        trainable = frozenset(
            n for n, p in block.collect_params().items()
            if p.grad_req != "null" and n in params)

        batch_spec = NamedSharding(mesh, P("dp"))
        repl = NamedSharding(mesh, P())
        # pin optimizer-state shardings: without this the first step's
        # outputs carry compiler-chosen shardings, every subsequent call
        # misses the jit cache and RECOMPILES the whole step
        opt_shardings = opt_shard(self.param_shardings, repl)
        self.opt_state = jax.tree_util.tree_map(
            global_device_put, self.opt_state, opt_shardings)

        if self.compression is None:
            def train_step(params, opt_state, *batch):
                inputs = batch[:self._n_inputs]
                labels = batch[self._n_inputs:]

                def loss_of(p):
                    out, aux = apply_fn(p, *inputs)
                    return loss_fn(out, *labels), aux

                (loss, aux), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(params)
                new_params, new_state = opt_update(params, grads,
                                                   opt_state, **opt_kw)
                # frozen params pass through untouched; aux states take
                # the forward-captured update (BatchNorm moving stats),
                # exactly like the eager/CachedOp paths
                new_params = {n: (v if n in trainable else params[n])
                              for n, v in new_params.items()}
                for n, v in aux.items():
                    if n in new_params:
                        new_params[n] = v.astype(new_params[n].dtype)
                return new_params, new_state, loss

            self._step = jax.jit(
                train_step,
                donate_argnums=(0, 1),
                out_shardings=(self.param_shardings, opt_shardings,
                               repl))
        else:
            self._build_compressed_step(
                apply_fn, loss_fn, opt_update, opt_kw, trainable,
                opt_shardings, repl)
        self._batch_spec = batch_spec

    def _build_compressed_step(self, apply_fn, loss_fn, opt_update,
                               opt_kw, trainable, opt_shardings, repl):
        """The quantized-allreduce variant of the train step: local
        grads under ``shard_map`` over dp, EF-quantized mean, optimizer
        outside the manual region.  Per-device residuals are state —
        donated and re-emitted every step like ``opt_state``."""
        mesh, spec = self.mesh, self.compression
        ndp = mesh.shape["dp"]
        comp_names = tuple(
            n for n in self.params
            if n in trainable
            and jnp.issubdtype(self.params[n].dtype, jnp.floating))
        comp_set = frozenset(comp_names)
        comp_index = {n: i for i, n in enumerate(comp_names)}
        res_sharding = NamedSharding(mesh, P("dp"))
        # residual leading axis = dp (each device's rounding error);
        # f32 regardless of param dtype (the EF accumulate-wide rule)
        self.residuals = {
            n: global_device_put(
                jnp.zeros((ndp,) + tuple(self.params[n].shape),
                          jnp.float32), res_sharding)
            for n in comp_names}
        res_shardings = {n: res_sharding for n in comp_names}
        n_inputs = self._n_inputs
        self._quant_step = 0

        def local_sync(p, res, key, *b):
            inputs = b[:n_inputs]
            labels = b[n_inputs:]

            def loss_of(p):
                out, aux = apply_fn(p, *inputs)
                return loss_fn(out, *labels), aux

            (loss, aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(p)
            dkey = None
            if spec.stochastic:
                dkey = jax.random.fold_in(key, lax.axis_index("dp"))
            synced, new_res = {}, {}
            for n, g in grads.items():
                if n in comp_set:
                    pkey = None if dkey is None else \
                        jax.random.fold_in(dkey, comp_index[n])
                    m, r = qz.allreduce_mean(g, res[n][0], spec, "dp",
                                             key=pkey)
                    synced[n] = m
                    new_res[n] = r[None]
                else:
                    synced[n] = lax.pmean(g, "dp")
            loss = lax.pmean(loss, "dp")
            # out_specs claims aux replicated (P()): every branch must
            # reduce, or each device keeps its own value silently
            # (shard_map_unchecked turns the runtime check off).  pmax
            # is dtype-preserving for the non-float stats — identity
            # when devices already agree, deterministic otherwise.
            aux = {n: (lax.pmean(v, "dp")
                       if jnp.issubdtype(v.dtype, jnp.floating)
                       else lax.pmax(v, "dp"))
                   for n, v in aux.items()}
            return synced, new_res, loss, aux

        sync = shard_map_unchecked(
            local_sync, mesh,
            in_specs=(P(), P("dp"), P())
            + (P("dp"),) * (n_inputs + self._n_labels),
            out_specs=(P(), P("dp"), P(), P()))

        def train_step(params, opt_state, residuals, key, *batch):
            synced, new_res, loss, aux = sync(params, residuals, key,
                                              *batch)
            new_params, new_state = opt_update(params, synced,
                                               opt_state, **opt_kw)
            new_params = {n: (v if n in trainable else params[n])
                          for n, v in new_params.items()}
            for n, v in aux.items():
                if n in new_params:
                    new_params[n] = v.astype(new_params[n].dtype)
            return new_params, new_state, new_res, loss

        self._step = jax.jit(
            train_step,
            donate_argnums=(0, 1, 2),
            out_shardings=(self.param_shardings, opt_shardings,
                           res_shardings, repl))
        # wire accounting, computed once: each of the dp devices
        # transmits its compressed contribution per step (vs the f32
        # payload the uncompressed allreduce would move)
        sizes = [int(self.params[n].size) for n in comp_names]
        self.wire_bytes_per_step = ndp * sum(
            qz.wire_bytes(s, spec) for s in sizes)
        self.logical_bytes_per_step = ndp * sum(
            qz.logical_bytes(s, self.params[n].dtype)
            for s, n in zip(sizes, comp_names))

    def shard_batch(self, *arrays):
        """Place host arrays batch-sharded over dp."""
        out = []
        for a in arrays:
            spec = P(*(["dp"] + [None] * (a.ndim - 1)))
            out.append(global_device_put(
                a, NamedSharding(self.mesh, spec)))
        return tuple(out)

    def step(self, *batch):
        """One compiled step; returns the (replicated) scalar loss.

        Under an active watchdog (``MXNET_TRAIN_STEP_TIMEOUT_MS`` /
        ``MXNET_TRAIN_SLOW_STEP_FACTOR``) the dispatch runs to DEVICE
        COMPLETION on a deadline thread: a wedged collective raises
        :class:`~.supervisor.TrainStepTimeoutError` inside the
        configured deadline instead of hanging the loop, and stragglers
        fire ``train.slow_steps``.  ``faults.inject("train.step")`` is
        the chaos hook for the whole step.

        With tracing or runtime metrics on, the step runs ATTRIBUTED
        (:meth:`_step_attributed`): each phase is timed into a
        ``train.*`` span and the step completes synchronously so the
        compute interval is real device time, not dispatch time."""
        if self.perf.active:
            return self._step_attributed(batch)
        batch = self.shard_batch(*[getattr(b, "_data", b) for b in batch])
        if self.watchdog.active:
            out = self.watchdog.watch(
                lambda: self._dispatch_step(batch, sync=True))
        else:
            out = self._dispatch_step(batch, sync=False)
        # commit on the CALLING thread only: after a watchdog timeout
        # the abandoned worker may eventually finish, and its output
        # must never clobber state the supervisor has since restored
        # from a checkpoint (run_with_deadline discards it instead)
        self.params, self.opt_state, residuals, quant_step, loss = out
        if residuals is not None:
            self.residuals = residuals
        if quant_step is not None:
            self._quant_step = quant_step
            if _rm._ENABLED:
                _rm.KV_WIRE_BYTES.inc(self.wire_bytes_per_step)
        return loss

    def _step_attributed(self, batch):
        """The observed variant of :meth:`step`: same commit protocol,
        but each phase lands in the ``train.step`` span tree and the
        breakdown histograms (docs/observability.md).  Runs with
        ``sync=True`` always — attribution needs the device interval,
        so async dispatch pipelining is given up while observing.
        ``train.collective``/``train.optimizer`` are zero-length
        markers: XLA fuses both into the one compiled step program
        measured as ``train.compute``."""
        # per-step FLOPs once per trainer, metrics-gated: AOT
        # lower().compile() — never enters the jit cache, so tracing
        # alone adds zero XLA programs
        if not self._flops_noted and _rm._ENABLED:
            self._flops_noted = True
            self.perf.note_flops(_pa.step_flops(self, batch))
        h = self.perf.step_start()
        with h:
            t0 = time.perf_counter()
            shardb = self.shard_batch(
                *[getattr(b, "_data", b) for b in batch])
            jax.block_until_ready(shardb)
            t1 = time.perf_counter()
            h.record("h2d", t0, t1)
            if self.watchdog.active:
                out = self.watchdog.watch(
                    lambda: self._dispatch_step(shardb, sync=True))
            else:
                out = self._dispatch_step(shardb, sync=True)
            if self.compression is not None:
                h.mark("collective", fused=True,
                       wire_bytes=self.wire_bytes_per_step,
                       logical_bytes=self.logical_bytes_per_step)
            else:
                h.mark("collective", fused=True)
            h.mark("optimizer", fused=True)
            self.params, self.opt_state, residuals, quant_step, loss = out
            if residuals is not None:
                self.residuals = residuals
            if quant_step is not None:
                self._quant_step = quant_step
                if _rm._ENABLED:
                    _rm.KV_WIRE_BYTES.inc(self.wire_bytes_per_step)
            # compute closes LAST so the ~us of marker/commit work
            # stays inside its interval and the phases tile the root
            h.record("compute", t1, time.perf_counter())
        return loss

    def _dispatch_step(self, batch, sync):
        """Pure with respect to trainer attributes — runs on the
        watchdog worker thread when a deadline is set, so it must only
        COMPUTE the new state and return it; ``step()`` commits."""
        # the fault site lives inside the watched call: a ``stall``
        # here is the wedged-collective shape the deadline must bound
        _faults.inject("train.step")
        if self.compression is None:
            params, opt_state, loss = self._step(
                self.params, self.opt_state, *batch)
            residuals = quant_step = None
        else:
            quant_step = self._quant_step + 1
            key = jax.random.PRNGKey(quant_step)
            params, opt_state, residuals, loss = \
                self._step(self.params, self.opt_state, self.residuals,
                           key, *batch)
        if sync:
            # the deadline must cover execution, not just dispatch —
            # async dispatch would "beat" any timeout while the wedged
            # collective hangs the NEXT host sync instead
            jax.block_until_ready(loss)
        return params, opt_state, residuals, quant_step, loss

    def extra_state(self):
        """Non-array step state for checkpoint ``extra`` payloads —
        the quantized-collective step counter seeds each step's
        stochastic-rounding key, so bit-exact resume must restore it."""
        if self.compression is not None:
            return {"quant_step": int(self._quant_step)}
        return {}

    def set_extra_state(self, state):
        if self.compression is not None and state \
                and "quant_step" in state:
            self._quant_step = int(state["quant_step"])

    def write_back(self):
        """Copy trained params back into the Block's Parameters."""
        for name, p in self.block.collect_params().items():
            if name in self.params:
                arr = p.data()
                arr._set_data(jax.device_put(
                    self.params[name],
                    arr._data.sharding if hasattr(arr._data, "sharding")
                    else None).astype(arr._data.dtype))
