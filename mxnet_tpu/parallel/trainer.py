"""ShardedTrainer: ONE compiled train step over a device mesh.

Replaces Trainer+kvstore at pod scale (SURVEY.md §3.4 TPU mapping): the
entire fwd+bwd+optimizer+allreduce is a single pjit program; XLA lowers
the gradient reductions to ICI/DCN collectives from the shardings alone.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from . import optim as _optim
from .functional import functionalize
from .sharding import MEGATRON_RULES, partition_params

__all__ = ["ShardedTrainer"]

def _sgd_shardings(ps, repl):
    return {"mom": dict(ps)}


def _adam_shardings(ps, repl):
    return {"mean": dict(ps), "var": dict(ps), "step": repl}


_OPTIMS = {
    "sgd": (_optim.sgd_init, _optim.sgd_update, _sgd_shardings),
    "adamw": (_optim.adamw_init, _optim.adamw_update, _adam_shardings),
    "lamb": (_optim.lamb_init, _optim.lamb_update, _adam_shardings),
}


class ShardedTrainer:
    """Compile a data+tensor-parallel training step for a Gluon block.

    loss_fn(outputs, *labels) -> scalar, written in jnp over raw arrays.
    Batch dims of inputs/labels are sharded over "dp"; params follow
    ``rules`` (default Megatron TP).  Donation gives in-place updates.
    """

    def __init__(self, block, loss_fn, mesh: Mesh, optimizer="adamw",
                 optimizer_params=None, rules=MEGATRON_RULES,
                 example_inputs=(), n_labels=1, dtype=None):
        if optimizer not in _OPTIMS:
            raise MXNetError(f"unknown optimizer {optimizer!r}; "
                             f"known: {sorted(_OPTIMS)}")
        self.mesh = mesh
        self.block = block
        opt_init, opt_update, opt_shard = _OPTIMS[optimizer]
        opt_kw = dict(optimizer_params or {})
        if "learning_rate" in opt_kw:
            opt_kw["lr"] = opt_kw.pop("learning_rate")
        if "weight_decay" in opt_kw:            # Gluon naming → optim's
            opt_kw["wd"] = opt_kw.pop("weight_decay")

        apply_fn, params = functionalize(block, *example_inputs,
                                         train_mode=True)
        # device_put below may ALIAS the Block's live buffers on
        # same-backend transfers; the step donates params, and donating
        # an aliased buffer deletes the imperative API's view (a later
        # wait_to_read/waitall then fails with "deleted or donated
        # buffer").  astype is a no-op alias when the dtype already
        # matches, so copy unconditionally in BOTH branches.
        def _own(a):
            if dtype is not None and jnp.issubdtype(a.dtype, jnp.floating):
                return jnp.array(a, dtype=dtype, copy=True)
            return jnp.array(a, copy=True)

        params = {n: _own(a) for n, a in params.items()}
        self.params, self.param_shardings = partition_params(
            params, mesh, rules)
        self.opt_state = opt_init(self.params)
        self._n_inputs = len(example_inputs)
        # aux/frozen params (grad_req='null': BatchNorm running stats,
        # positional constants) must NOT receive optimizer updates — with
        # zero grads the weight-decay term would silently erode them
        trainable = frozenset(
            n for n, p in block.collect_params().items()
            if p.grad_req != "null" and n in params)

        batch_spec = NamedSharding(mesh, P("dp"))
        repl = NamedSharding(mesh, P())
        # pin optimizer-state shardings: without this the first step's
        # outputs carry compiler-chosen shardings, every subsequent call
        # misses the jit cache and RECOMPILES the whole step
        opt_shardings = opt_shard(self.param_shardings, repl)
        self.opt_state = jax.tree_util.tree_map(
            lambda a, s: jax.device_put(a, s), self.opt_state,
            opt_shardings)

        def train_step(params, opt_state, *batch):
            inputs = batch[:self._n_inputs]
            labels = batch[self._n_inputs:]

            def loss_of(p):
                out, aux = apply_fn(p, *inputs)
                return loss_fn(out, *labels), aux

            (loss, aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            new_params, new_state = opt_update(params, grads, opt_state,
                                               **opt_kw)
            # frozen params pass through untouched; aux states take the
            # forward-captured update (BatchNorm moving stats), exactly
            # like the eager/CachedOp paths
            new_params = {n: (v if n in trainable else params[n])
                          for n, v in new_params.items()}
            for n, v in aux.items():
                if n in new_params:
                    new_params[n] = v.astype(new_params[n].dtype)
            return new_params, new_state, loss

        self._step = jax.jit(
            train_step,
            donate_argnums=(0, 1),
            out_shardings=(self.param_shardings, opt_shardings, repl))
        self._batch_spec = batch_spec

    def shard_batch(self, *arrays):
        """Place host arrays batch-sharded over dp."""
        out = []
        for a in arrays:
            spec = P(*(["dp"] + [None] * (a.ndim - 1)))
            out.append(jax.device_put(a, NamedSharding(self.mesh, spec)))
        return tuple(out)

    def step(self, *batch):
        """One compiled step; returns the (replicated) scalar loss."""
        batch = self.shard_batch(*[getattr(b, "_data", b) for b in batch])
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, *batch)
        return loss

    def write_back(self):
        """Copy trained params back into the Block's Parameters."""
        for name, p in self.block.collect_params().items():
            if name in self.params:
                arr = p.data()
                arr._set_data(jax.device_put(
                    self.params[name],
                    arr._data.sharding if hasattr(arr._data, "sharding")
                    else None).astype(arr._data.dtype))
