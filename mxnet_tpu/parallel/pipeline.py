"""Pipeline parallelism: GPipe microbatch schedule over a ``pp`` axis.

New TPU-first capability (SURVEY.md §2.4: upstream has NO pipeline
parallelism — its closest construct, BucketingModule, is dynamic-shape
handling).  Stages live on different devices along a mesh axis; micro-
batches flow stage-to-stage via ``lax.ppermute`` on ICI neighbors inside
one compiled program.  The schedule is the classic GPipe fill-drain:
``T = n_micro + n_stages - 1`` ticks, stage ``p`` processing microbatch
``t - p`` at tick ``t``; expressed as ``lax.scan`` (static shapes, no
data-dependent python control flow), so it jits, differentiates
(reverse-mode replays the schedule backwards — the cotangent ppermutes
ride the reverse ring), and composes with dp/tp on the other mesh axes.

Uniform-stage contract: every stage maps activations of one fixed
(shape, dtype) to the same (shape, dtype) — the hand-off buffer between
neighbors is a single static aval.  (Megatron-style transformer stacks
satisfy this by construction.)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError

from .._jax_compat import shard_map, to_varying

__all__ = ["pipeline_apply", "make_pipeline_mesh"]


def make_pipeline_mesh(n_stages, devices=None) -> Mesh:
    """A 1-D mesh whose single axis is the pipeline (``pp``)."""
    import numpy as np
    if devices is None:
        devices = jax.devices()
    if len(devices) < n_stages:
        raise MXNetError(f"pipeline of {n_stages} stages needs "
                         f"{n_stages} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n_stages]), axis_names=("pp",))


def pipeline_apply(stage_fn, stage_params, micro_inputs, mesh: Mesh,
                   axis: str = "pp"):
    """Run ``micro_inputs`` through the stage pipeline.

    stage_fn(params, x) -> y with ``y.shape == x.shape`` and same dtype
    (uniform-stage contract).  ``stage_params``: pytree whose leaves have
    a leading stage dimension of size ``mesh.shape[axis]`` (sharded over
    ``axis``).  ``micro_inputs``: (n_micro, micro_batch, ...).  Returns
    (n_micro, micro_batch, ...) outputs of the LAST stage, replicated.
    """
    n_stages = mesh.shape[axis]
    n_micro = micro_inputs.shape[0]
    T = n_micro + n_stages - 1

    def _varying(x):
        # newer shard_map tracks varying-manual-axes: scan carries that
        # BECOME pp-varying must start pp-varying
        return to_varying(x, axis)

    def per_device(params_stage, xs):
        # params_stage leaves: (1, ...) — this device's stage slice
        params_local = jax.tree_util.tree_map(lambda a: a[0],
                                              params_stage)
        p = lax.axis_index(axis)
        buf0 = _varying(jnp.zeros(xs.shape[1:], xs.dtype))
        outs0 = _varying(jnp.zeros_like(xs))

        def tick(state, t):
            buf, outs = state
            m = t - p                       # microbatch this stage sees
            active = (m >= 0) & (m < n_micro)
            x_in = jnp.where(p == 0,
                             xs[jnp.clip(t, 0, n_micro - 1)], buf)
            y = stage_fn(params_local, x_in)
            # zero inactive ticks so garbage never propagates
            y = jnp.where(active, y, jnp.zeros_like(y))
            if n_stages > 1:
                # mxlint: disable=collective-soundness (deliberately
                # non-total: the GPipe hand-off sends stage i -> i+1 and
                # must NOT wrap the last stage back to 0 — stage 0 reads
                # fresh microbatches from xs, and ppermute zero-fills
                # un-received buffers, which `active` masking discards)
                sent = lax.ppermute(
                    y, axis,
                    perm=[(i, i + 1) for i in range(n_stages - 1)])
            else:
                sent = y
            m_out = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            take = active & (p == n_stages - 1)
            outs = outs.at[m_out].set(
                jnp.where(take, y, outs[m_out]))
            return (buf if n_stages == 1 else sent, outs), None

        (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(T))
        # only the last stage holds real outputs: replicate via psum of
        # the masked buffer (identity when n_stages == 1)
        mask = (p == n_stages - 1).astype(outs.dtype)
        return lax.psum(outs * mask, axis)

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(pspec, P()), out_specs=P())
    return fn(stage_params, micro_inputs)
