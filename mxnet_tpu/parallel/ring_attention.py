"""Ring attention: context/sequence parallelism over the ICI ring.

New capability beyond reference parity (SURVEY.md §5.7: the reference's
attention is O(L^2) single-device).  Sequence is sharded over a mesh axis;
each device holds a Q block and rotates K/V blocks around the ring with
``lax.ppermute``, accumulating softmax online (flash-attention style), so
memory is O(L_local) and the KV transfers overlap compute on ICI.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from .._jax_compat import shard_map, to_varying

__all__ = ["ring_attention", "ring_self_attention"]


def _ring_attention_local(q, k, v, q_pos, k_pos, axis_name, causal, scale,
                          window=None):
    """Per-device body under shard_map.

    q (B, H, Lq, D); k/v (B, H, Lk, D); *_pos (Lq,)/(Lk,) global token
    positions (positions travel with the rotating kv so causal masking
    stays correct on every hop).

    ``window``: causal sliding window — key positions in
    ``(q_pos - window, q_pos]`` attend.  Ring hops whose rotating KV
    block lies entirely outside every local query's band SKIP their
    attention compute via ``lax.cond`` (the rotation itself still runs:
    the ring schedule is fixed); with S shards and window W, each
    device pays for ~``ceil(W / L_loc) + 1`` hops of compute instead
    of S.
    """
    axis_size = lax.psum(1, axis_name)
    B, H, Lq, D = q.shape
    neg_inf = jnp.asarray(-1e30, dtype=jnp.float32)

    m0 = jnp.full((B, H, Lq), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((B, H, Lq), dtype=jnp.float32)
    acc0 = jnp.zeros((B, H, Lq, D), dtype=jnp.float32)
    # constants start axis-unvarying under shard_map's vma typing;
    # the loop carry becomes varying, so pre-cast the initial carry
    m0, l0, acc0 = (to_varying(x, axis_name) for x in (m0, l0, acc0))

    def attend(m, l, acc, k, v, k_pos):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = k_pos[None, :] > q_pos[:, None]        # (Lq, Lk)
            if window is not None:
                mask = mask | (k_pos[None, :] <= q_pos[:, None] - window)
            s = jnp.where(mask[None, None], neg_inf, s)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
        return m_new, l_new, acc_new

    def body(i, carry):
        m, l, acc, k, v, k_pos = carry
        if window is None:
            m, l, acc = attend(m, l, acc, k, v, k_pos)
        else:
            # band-overlap test for THIS hop's kv block: any (q, k)
            # with q - window < k_pos <= q_pos?
            needed = (jnp.min(k_pos) <= jnp.max(q_pos)) & \
                (jnp.max(k_pos) > jnp.min(q_pos) - window)
            m, l, acc = lax.cond(
                needed,
                lambda args: attend(*args, k, v, k_pos),
                lambda args: args,
                (m, l, acc))
        # rotate kv (and its positions) one hop around the ring
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        k_pos = lax.ppermute(k_pos, axis_name, perm)
        return m, l, acc, k, v, k_pos

    m, l, acc, _, _, _ = lax.fori_loop(
        0, axis_size, body, (m0, l0, acc0, k, v, k_pos))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis_name="sp", causal=False,
                   window=None):
    """Sharded attention over sequence: q/k/v (B, H, L, D) with L sharded
    on ``axis_name``.  Returns (B, H, L, D) with the same sharding.

    ``window``: causal sliding-window width (key positions in
    ``(q - window, q]``); requires ``causal=True``.  Out-of-band ring
    hops skip their attention compute, so cost scales with the window,
    not the full context."""
    if window is not None:
        from ..base import MXNetError
        if not causal:
            raise MXNetError("ring_attention: window= requires "
                             "causal=True (sliding-window attention is "
                             "causal)")
        if int(window) < 1:
            raise MXNetError("ring_attention: window must be >= 1")
    n = mesh.shape[axis_name]
    B, H, L, D = q.shape
    scale = 1.0 / (D ** 0.5)
    L_loc = L // n

    qkv_spec = P(None, None, axis_name, None)
    pos = jnp.arange(L, dtype=jnp.int32)

    def local_fn(q, k, v, q_pos, k_pos):
        return _ring_attention_local(q, k, v, q_pos, k_pos, axis_name,
                                     causal, scale, window=window)

    fn = shard_map(
        local_fn, mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, P(axis_name), P(axis_name)),
        out_specs=qkv_spec)
    return fn(q, k, v, pos, pos)


def ring_self_attention(x, w_qkv, w_out, num_heads, mesh, axis_name="sp",
                        causal=True, window=None):
    """x (B, L, C) sequence-sharded -> same; projections computed locally
    (they're pointwise over sequence)."""
    B, L, C = x.shape
    D = C // num_heads
    qkv = jnp.einsum("blc,oc->blo", x, w_qkv)      # (B, L, 3C)
    qkv = qkv.reshape(B, L, 3, num_heads, D)
    q = qkv[:, :, 0].transpose(0, 2, 1, 3)
    k = qkv[:, :, 1].transpose(0, 2, 1, 3)
    v = qkv[:, :, 2].transpose(0, 2, 1, 3)
    out = ring_attention(q, k, v, mesh, axis_name, causal, window=window)
    out = out.transpose(0, 2, 1, 3).reshape(B, L, C)
    return jnp.einsum("blc,oc->blo", out, w_out)
