"""Self-healing training plane: step watchdog + supervised restarts
(docs/training_resilience.md).

The serving plane already proves the kill -> detect -> restore ->
resume -> verify ladder (faults + deadlines + breakers + failover,
docs/serving.md §8/§10); this module is the same ladder on the
training plane, where the failure shapes are different: a wedged
collective does not error, it HANGS the one thread the whole loop
runs on, and a crash does not lose a request, it loses every step
since the last durable checkpoint — then a naive restart silently
replays or skips data.  Three pieces close those holes:

- :class:`TrainStepTimeoutError` + :func:`run_with_deadline` — a
  compiled step runs under a watchdog deadline
  (``MXNET_TRAIN_STEP_TIMEOUT_MS``); a step that does not complete in
  time raises the typed, ``transient``-marked error instead of
  hanging forever.  The stuck dispatch is left behind on an abandoned
  daemon thread (a wedged XLA collective cannot be cancelled from
  Python); the supervisor's restore path makes its eventual output
  irrelevant.
- :class:`StepWatchdog` — per-trainer deadline + straggler detection:
  a step slower than ``MXNET_TRAIN_SLOW_STEP_FACTOR`` x the rolling
  median step time increments ``train.slow_steps`` and dumps a
  flight-recorder incident (the slow-step -> dead-step progression is
  how TPU preemptions and failing hosts actually announce themselves).
- :class:`TrainingSupervisor` — wraps the train loop with a
  bounded-restart policy.  On a TRANSIENT failure (``exc.transient``
  truthy — injected faults, step timeouts, device blips) it sleeps a
  jittered exponential backoff, restores the newest VERIFIED
  checkpoint (:meth:`CheckpointManager.restore`'s torn-payload
  fallback included), rewinds the eager RNG stream and the data
  iterator's cursor from the checkpoint's extra payload, and resumes
  — **bit-exactly**: the resumed loss trajectory is identical to an
  uninterrupted run's, because every input to step k (params, opt
  state, residuals, RNG key, batch k) is restored, not approximated.
  Deterministic failures re-raise immediately — restarting a shape
  mismatch just burns restarts.  More than
  ``MXNET_TRAIN_MAX_RESTARTS`` consecutive failures without a
  completed step trips the crash-loop breaker
  (:class:`CrashLoopError`); any completed step resets the run.

State machine::

    RUNNING --transient failure--> BACKOFF --> RESTORE --> RUNNING
    RUNNING --deterministic failure--> FAILED       (re-raise)
    BACKOFF --consec > MXNET_TRAIN_MAX_RESTARTS--> CRASH_LOOP

Observability: ``train.restarts`` / ``train.recovery.seconds`` /
``train.step.timeouts`` / ``train.slow_steps`` in ``runtime_metrics``,
plus :meth:`TrainingSupervisor.debug_state` attached to every restart
incident dump.

Threading contract: a supervisor (and a trainer's watchdog) belongs to
ONE train-loop thread; only :func:`run_with_deadline`'s internal
worker thread is ever concurrent, and it communicates through a
single-assignment box + Event.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque

from .. import engine as _engine
from .. import perf_account as _pa
from .. import runtime_metrics as _rm, tracing as _tr
from ..base import MXNetError, entropy_rng, get_env

__all__ = ["TrainStepTimeoutError", "CrashLoopError", "StepWatchdog",
           "run_with_deadline", "TrainingSupervisor"]

_LOG = logging.getLogger("mxnet_tpu")


class TrainStepTimeoutError(MXNetError):
    """A watched train step missed its watchdog deadline (wedged
    collective, stuck device, dead peer).  ``transient`` marks it
    restartable to the supervisor: the canonical cause is a peer/
    interconnect fault that a restore + re-run absorbs."""

    transient = True

    def __init__(self, site, timeout_ms):
        self.site = site
        self.timeout_ms = timeout_ms
        super().__init__(
            f"{site}: no completion within {timeout_ms:g}ms watchdog "
            f"deadline (wedged collective / stuck device)")


class CrashLoopError(MXNetError):
    """The supervisor's crash-loop breaker: more consecutive failed
    restart cycles than ``MXNET_TRAIN_MAX_RESTARTS`` without one
    completed step.  At that point the failure is not transient no
    matter what it claims — re-restoring the same state into the same
    fault forever is the training-plane retry storm."""

    def __init__(self, restarts, last_error):
        self.restarts = restarts
        self.last_error = last_error
        super().__init__(
            f"train loop crash-looping: {restarts} restart(s) without "
            f"progress; last error: {last_error!r}")


def run_with_deadline(fn, timeout_ms, site="train.step"):
    """Run ``fn()`` under a watchdog deadline; raise
    :class:`TrainStepTimeoutError` if it does not complete in
    ``timeout_ms``.  ``timeout_ms <= 0`` calls ``fn`` directly (the
    zero-cost off path).

    The deadline is enforced by running ``fn`` on a daemon worker
    thread and waiting on an Event: a wedged ``fn`` cannot be
    cancelled from Python, so on timeout the worker is ABANDONED
    (it parks on the blocked call; if it ever finishes, its result is
    discarded and the thread exits).  Callers that time out must not
    trust any state ``fn`` was mutating — the supervisor restores
    from the last verified checkpoint for exactly this reason."""
    if not timeout_ms or timeout_ms <= 0:
        return fn()
    box = {}
    done = threading.Event()

    def _worker():
        try:
            box["value"] = fn()
        except BaseException as e:          # noqa: BLE001 — re-raised
            box["error"] = e
        finally:
            done.set()

    worker = _engine.make_thread(
        _worker, name=f"mxnet-watchdog-{site}", owner="run_with_deadline")
    worker.start()
    if not done.wait(timeout_ms / 1e3):
        if _rm._ENABLED:
            _rm.TRAIN_STEP_TIMEOUTS.inc()
        _tr.record_incident(
            f"train.step_timeout: {site}",
            {"site": site, "timeout_ms": timeout_ms})
        # the wedged step is deliberately abandoned (daemonized by
        # construction): joining it would just relocate the hang
        _engine.forget_thread(
            worker, f"wedged past {timeout_ms}ms deadline at {site}")
        raise TrainStepTimeoutError(site, timeout_ms)
    worker.join()           # done is set: the join is immediate
    if "error" in box:
        raise box["error"]
    return box["value"]


class StepWatchdog:
    """Deadline + straggler detection for one trainer's ``step()``.

    ``timeout_ms``/``slow_factor`` default from
    ``MXNET_TRAIN_STEP_TIMEOUT_MS`` / ``MXNET_TRAIN_SLOW_STEP_FACTOR``;
    both 0 means :attr:`active` is False and callers skip the wrapper
    entirely.  Straggler rule: with >= 5 observations banked, a step
    slower than ``slow_factor`` x the rolling median fires
    ``train.slow_steps`` plus one flight-recorder incident.  Owned by
    one train-loop thread (no internal locking)."""

    def __init__(self, timeout_ms=None, slow_factor=None, window=32,
                 site="train.step"):
        self.timeout_ms = float(
            get_env("MXNET_TRAIN_STEP_TIMEOUT_MS", typ=float) or 0.0
            if timeout_ms is None else timeout_ms)
        self.slow_factor = float(
            get_env("MXNET_TRAIN_SLOW_STEP_FACTOR", typ=float) or 0.0
            if slow_factor is None else slow_factor)
        self.site = site
        self.timeouts = 0
        self.slow_steps = 0
        self._times = deque(maxlen=int(window))

    @property
    def active(self):
        return self.timeout_ms > 0 or self.slow_factor > 0

    def watch(self, fn):
        """Run one step under the deadline, then feed its duration to
        the straggler detector.  Timings are host wall-clock of the
        WATCHED call — under a deadline the call includes device
        completion, so the duration is the real step time."""
        t0 = time.perf_counter()
        try:
            out = run_with_deadline(fn, self.timeout_ms, self.site)
        except TrainStepTimeoutError:
            self.timeouts += 1
            # lands on the enclosing train.step span when the step is
            # attributed — the timeout shows up in the trace timeline
            _tr.tag("watchdog_timeout_ms", self.timeout_ms)
            raise
        self._observe(time.perf_counter() - t0)
        return out

    def _observe(self, dt):
        if self.slow_factor > 0 and len(self._times) >= 5:
            med = sorted(self._times)[len(self._times) // 2]
            if med > 0 and dt > self.slow_factor * med:
                self.slow_steps += 1
                if _rm._ENABLED:
                    _rm.TRAIN_SLOW_STEPS.inc()
                _tr.tag("slow_step", round(dt, 6))
                _tr.record_incident(
                    f"train.slow_step: {dt * 1e3:.1f}ms vs median "
                    f"{med * 1e3:.1f}ms",
                    {"site": self.site, "step_seconds": dt,
                     "median_seconds": med, "factor": self.slow_factor,
                     "verdict": _pa.current_verdict()})
        self._times.append(dt)

    def debug_state(self):
        times = sorted(self._times)
        return {"site": self.site, "timeout_ms": self.timeout_ms,
                "slow_factor": self.slow_factor,
                "timeouts": self.timeouts,
                "slow_steps": self.slow_steps,
                "observed": len(times),
                "median_ms": (times[len(times) // 2] * 1e3
                              if times else None)}


def _is_transient(exc):
    """The serving plane's ``resilience.is_transient`` contract, kept
    local so importing the training plane never pulls in the serving
    stack: only failures that opt in via a truthy ``exc.transient``
    (InjectedFault, TrainStepTimeoutError, real device blips) may be
    absorbed by a restart."""
    return bool(getattr(exc, "transient", False))


def _default_step_fn(trainer, batch):
    """One step from a reference ``DataBatch``: positional data then
    labels, matching ``ShardedTrainer.step(*inputs, *labels)``."""
    args = list(batch.data) + list(batch.label or [])
    return trainer.step(*args)


class TrainingSupervisor:
    """Run a train loop to completion through transient failures.

    ``trainer`` needs ``step``-compatible semantics plus the
    checkpointable surface ``CheckpointManager`` already uses
    (``params``/``opt_state``; optional ``extra_state()`` /
    ``set_extra_state()`` for e.g. the quantized-collective step
    counter).  ``manager`` is a :class:`~.checkpoint.CheckpointManager`.
    ``data_iter`` is a reference ``DataIter``; epoch ends
    (StopIteration) reset and continue.  Bit-exact resume additionally
    needs the iterator to expose ``get_cursor()``/``set_cursor()``
    (``io.NDArrayIter(seed=...)``) — without it the supervisor still
    restarts, but warns that resume may replay or skip batches.

    ``run(num_steps)`` returns the loss trajectory (one float per
    completed step, global step order); every restart truncates it
    back to the restored step so the returned list is exactly what an
    uninterrupted run would have produced.
    """

    def __init__(self, trainer, manager, data_iter=None, *,
                 step_fn=None, save_every=50, max_restarts=None,
                 backoff_ms=None, backoff_max_ms=None,
                 auto_resume=True, rng=None):
        self.trainer = trainer
        self.manager = manager
        self.save_every = int(save_every)
        self.auto_resume = bool(auto_resume)
        self._iter = data_iter
        self._step_fn = step_fn or _default_step_fn
        self._max_restarts = int(
            get_env("MXNET_TRAIN_MAX_RESTARTS", typ=int)
            if max_restarts is None else max_restarts)
        self._backoff_ms = float(
            get_env("MXNET_TRAIN_RESTART_BACKOFF_MS", typ=float)
            if backoff_ms is None else backoff_ms)
        self._backoff_max_ms = float(
            get_env("MXNET_TRAIN_RESTART_BACKOFF_MAX_MS", typ=float)
            if backoff_max_ms is None else backoff_max_ms)
        # jitter only — never correctness; seedable for tests
        self._rng = rng if rng is not None else entropy_rng()
        self._step = 0                  # completed steps from origin
        self._losses = []
        self._restarts = 0              # lifetime restore+restart count
        self._consec = 0    # failures since the last completed step
        self._tripped = False
        self._last_error = None
        self._recovery_total = 0.0
        self._cursor_warned = False
        if data_iter is not None and not hasattr(data_iter,
                                                 "get_cursor"):
            _LOG.warning(
                "supervisor: data iterator %s has no cursor "
                "(get_cursor/set_cursor) — resume after a restart may "
                "replay or skip batches; use io.NDArrayIter(seed=...) "
                "or another checkpointable iterator for bit-exact "
                "resume", type(data_iter).__name__)

    # ------------------------------------------------------------ the loop
    def run(self, num_steps):
        """Supervised training to ``num_steps`` completed steps."""
        num_steps = int(num_steps)
        resumed = False
        pending = None
        # ONE try covers the whole attempt — including auto-resume,
        # the anchor save, and the previous failure's recovery — so a
        # transient blip during recovery itself (checkpoint.restore
        # fault, storage hiccup) re-enters the restart policy and is
        # bounded by the crash-loop breaker instead of escaping
        while True:
            try:
                if pending is not None:
                    exc, pending = pending, None
                    self._handle_transient(exc)
                if not resumed:
                    resumed = True
                    if self._step == 0 and self.manager \
                            .latest_verified_step() is not None \
                            and self.auto_resume:
                        self._recover()     # pick up a preempted run
                if self.manager.latest_verified_step() is None:
                    # the restore anchor: a failure before the first
                    # periodic checkpoint must still rewind to a
                    # bit-exact start
                    self._save(0)
                self._run_loop(num_steps)
                # a resume may pick up a checkpoint already past
                # num_steps; the contract is one loss per requested step
                return list(self._losses[:num_steps])
            except Exception as e:  # noqa: BLE001 — policy filter below
                if not _is_transient(e):
                    raise
                pending = e

    def _run_loop(self, num_steps):
        while self._step < num_steps:
            batch = self._next_batch()
            loss = self._step_fn(self.trainer, batch)
            self._losses.append(float(loss))
            self._step += 1
            self._consec = 0    # progress resets the crash-loop run
            if self.save_every and self._step % self.save_every == 0 \
                    and self._step < num_steps:
                self._save(self._step)
        if self._step == num_steps \
                and self.manager.latest_verified_step() != num_steps:
            self._save(num_steps)       # durable finish

    def _next_batch(self):
        if self._iter is None:
            return None
        try:
            return self._iter.next()
        except StopIteration:
            self._iter.reset()
            return self._iter.next()

    # -------------------------------------------------------- checkpointing
    def _save(self, step):
        from .. import random as _random
        # the FULL trajectory rides every sidecar: it is what lets a
        # cross-process resume return the same loss list as an
        # uninterrupted run (retention GC deletes older sidecars, so a
        # tail-only scheme could not reconstruct the prefix).  Cost is
        # O(steps) JSON per barrier — for very long runs, raise
        # save_every rather than shrinking this payload
        extra = {"step": int(step),
                 "rng": _random.get_state(),
                 "losses": list(self._losses),
                 "cursor": None, "trainer": None}
        get_cursor = getattr(self._iter, "get_cursor", None)
        if get_cursor is not None:
            try:
                extra["cursor"] = get_cursor()
            except MXNetError as e:
                # e.g. a shuffling NDArrayIter without seed= — degrade
                # to the documented restart-without-bit-exactness path
                # rather than failing the save
                if not self._cursor_warned:
                    self._cursor_warned = True
                    _LOG.warning(
                        "supervisor: data-iterator cursor unavailable "
                        "(%s) — resume after a restart may replay or "
                        "skip batches", e)
        trainer_extra = getattr(self.trainer, "extra_state", None)
        if trainer_extra is not None:
            extra["trainer"] = trainer_extra()
        self.manager.save(step, self.trainer, extra=extra)
        # the barrier makes save_every the VERIFIED cadence: each
        # periodic save is durable (manifest + marker) before the loop
        # continues, so it is always a legal restore target
        # mxlint: disable=deadline-soundness (contract: the durability
        # barrier must complete before the marker advances — a deadline
        # here would tear the checkpoint; the job tier (dist.Watchdog /
        # the launcher) bounds a wedged backend)
        self.manager.wait()

    def _recover(self):
        from .. import random as _random
        try:
            step = self.manager.restore(self.trainer)
        except MXNetError:
            if self._step == 0 and not self._losses:
                # nothing restorable AND nothing mutated yet (the
                # failure hit before the step-0 anchor landed): the
                # initial state is still the bit-exact start
                _LOG.warning("supervisor: nothing restorable yet — "
                             "restarting from the initial state")
                return
            raise
        extra = self.manager.load_extra(step) or {}
        if extra.get("rng") is not None:
            _random.set_state(extra["rng"])
        cursor = extra.get("cursor")
        set_cursor = getattr(self._iter, "set_cursor", None)
        if cursor is not None and set_cursor is not None:
            set_cursor(cursor)
        set_extra = getattr(self.trainer, "set_extra_state", None)
        if set_extra is not None:
            set_extra(extra.get("trainer") or {})
        losses = extra.get("losses")
        self._losses = ([float(v) for v in losses]
                        if losses is not None
                        else self._losses[:int(step)])
        self._step = int(step)
        _LOG.warning("supervisor: restored to verified step %d", step)

    # ----------------------------------------------------- failure handling
    def _handle_transient(self, exc):
        self._consec += 1
        self._last_error = repr(exc)
        if self._consec > self._max_restarts:
            self._tripped = True
            raise CrashLoopError(self._restarts, exc) from exc
        self._restarts += 1
        if _rm._ENABLED:
            _rm.TRAIN_RESTARTS.inc()
        _tr.record_incident(f"train.restart: {exc}", self.debug_state)
        delay = min(self._backoff_ms * 2 ** (self._consec - 1),
                    self._backoff_max_ms) / 1e3 \
            * (0.5 + self._rng.random() / 2.0)
        _LOG.warning(
            "supervisor: transient train failure (%s) — restart "
            "%d (consecutive %d/%d) after %.0fms backoff", exc,
            self._restarts, self._consec, self._max_restarts,
            delay * 1e3)
        if delay > 0:
            # mxlint: disable=deadline-soundness (contract: restart
            # backoff, bounded by MXNET_TRAIN_RESTART_BACKOFF_MAX_MS
            # per sleep and by the crash-loop breaker in total — the
            # training plane has no request deadline to consume)
            time.sleep(delay)
        t0 = time.perf_counter()
        self._recover()
        recovery = time.perf_counter() - t0
        self._recovery_total += recovery
        if _rm._ENABLED:
            _rm.TRAIN_RECOVERY_SECONDS.observe(recovery)

    # ------------------------------------------------------------- readers
    @property
    def losses(self):
        return list(self._losses)

    @property
    def restarts(self):
        return self._restarts

    def debug_state(self):
        state = {"step": self._step,
                 "restarts": self._restarts,
                 "consecutive_failures": self._consec,
                 "max_restarts": self._max_restarts,
                 "crash_loop_tripped": self._tripped,
                 "last_error": self._last_error,
                 "recovery_seconds_total": self._recovery_total,
                 "latest_verified_step":
                     self.manager.latest_verified_step(),
                 "losses": len(self._losses),
                 "save_every": self.save_every}
        watchdog = getattr(self.trainer, "watchdog", None)
        if watchdog is not None:
            state["watchdog"] = watchdog.debug_state()
        perf = getattr(self.trainer, "perf", None)
        if perf is not None:
            state["perf"] = perf.debug_state()
        return state
