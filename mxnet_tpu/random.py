"""RNG management + random sampling ops.

Reference: ``src/operator/random/`` (samplers over cuRAND/mkl resources,
``ResourceRequest::kRandom``) and ``python/mxnet/random.py`` (``mx.random.seed``).

TPU-native redesign: JAX threefry counter-based PRNG.

- Eager mode: a process-global key, split per draw (``mx.random.seed`` resets
  it) — matching the reference's stateful-sampler UX.
- Traced mode (hybridize/CachedOp): drawing from global state would bake one
  sample into the compiled program, so while a trace is active ``next_key()``
  yields ``fold_in(trace_key, counter)`` where ``trace_key`` is a *traced
  input* the CachedOp feeds a fresh key every call (see gluon/block.py).
  This keeps op signatures reference-compatible (no explicit key argument)
  while staying pure under jit — the TPU equivalent of the reference's
  per-device ``kParallelRandom`` resource.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from .base import get_env
from .ops.registry import register

__all__ = ["seed", "next_key", "trace_key_scope", "get_state",
           "set_state", "uniform", "normal", "randint", "randn"]


class _RandState(threading.local):
    def __init__(self):
        self.key = None
        self.trace_key = None
        self.trace_counter = 0


_STATE = _RandState()


def _global_key():
    if _STATE.key is None:
        s = get_env("MXNET_SEED")
        _STATE.key = jax.random.PRNGKey(int(s) if s is not None else 0)
    return _STATE.key


def seed(seed_state: int, ctx: str = "all"):
    """Reference: mx.random.seed — reseed the global generator."""
    _STATE.key = jax.random.PRNGKey(int(seed_state))
    _STATE.trace_counter = 0


def get_state():
    """Snapshot of this thread's eager PRNG stream as plain host data
    (JSON-serializable), for checkpoint/resume: restoring it with
    :func:`set_state` makes the subsequent draw sequence bit-identical
    to what an uninterrupted run would have produced.  Counter-based
    threefry makes this tiny — the whole stream is one key."""
    import numpy as np
    # the global key is a raw uint32 PRNGKey array (threefry data)
    return {"key": [int(v) for v in np.asarray(_global_key()).ravel()],
            "trace_counter": _STATE.trace_counter}


def set_state(state):
    """Restore a :func:`get_state` snapshot into this thread's eager
    PRNG (the checkpoint-resume half of the bit-exact contract)."""
    import numpy as np
    _STATE.key = jnp.asarray(np.array(state["key"], dtype=np.uint32))
    _STATE.trace_counter = int(state.get("trace_counter", 0))


def next_key():
    """Next PRNG key: trace-aware (see module docstring)."""
    if _STATE.trace_key is not None:
        k = jax.random.fold_in(_STATE.trace_key, _STATE.trace_counter)
        _STATE.trace_counter += 1
        return k
    new_key, sub = jax.random.split(_global_key())
    _STATE.key = new_key
    return sub


class trace_key_scope:
    """Installs a traced key for the duration of a trace (used by CachedOp)."""

    def __init__(self, key):
        self._key = key
        self._saved = None

    def __enter__(self):
        self._saved = (_STATE.trace_key, _STATE.trace_counter)
        _STATE.trace_key = self._key
        _STATE.trace_counter = 0
        return self

    def __exit__(self, *exc):
        _STATE.trace_key, _STATE.trace_counter = self._saved
        return False


# ---------------------------------------------------------------------------
# Sampling ops (reference: src/operator/random/sample_op.cc).  Zero-input
# ops with shape/dtype params, like the reference `_random_*` family.
# ---------------------------------------------------------------------------

def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


@register("_random_uniform", num_inputs=0, differentiable=False,
          mutates_rng=True, aliases=["random_uniform"])
def _random_uniform(*, low: float = 0.0, high: float = 1.0, shape=None,
                    dtype: str = "float32", ctx: str = ""):
    return jax.random.uniform(next_key(), _shape(shape),
                              dtype=jnp.dtype(dtype), minval=low, maxval=high)


@register("_random_normal", num_inputs=0, differentiable=False,
          mutates_rng=True, aliases=["random_normal"])
def _random_normal(*, loc: float = 0.0, scale: float = 1.0, shape=None,
                   dtype: str = "float32", ctx: str = ""):
    return loc + scale * jax.random.normal(next_key(), _shape(shape),
                                           dtype=jnp.dtype(dtype))


@register("_random_gamma", num_inputs=0, differentiable=False,
          mutates_rng=True, aliases=["random_gamma"])
def _random_gamma(*, alpha: float = 1.0, beta: float = 1.0, shape=None,
                  dtype: str = "float32", ctx: str = ""):
    return beta * jax.random.gamma(next_key(), alpha, _shape(shape),
                                   dtype=jnp.dtype(dtype))


@register("_random_exponential", num_inputs=0, differentiable=False,
          mutates_rng=True, aliases=["random_exponential"])
def _random_exponential(*, lam: float = 1.0, shape=None,
                        dtype: str = "float32", ctx: str = ""):
    return jax.random.exponential(next_key(), _shape(shape),
                                  dtype=jnp.dtype(dtype)) / lam


@register("_random_poisson", num_inputs=0, differentiable=False,
          mutates_rng=True, aliases=["random_poisson"])
def _random_poisson(*, lam: float = 1.0, shape=None, dtype: str = "float32",
                    ctx: str = ""):
    return jax.random.poisson(next_key(), lam, _shape(shape)).astype(
        jnp.dtype(dtype))


@register("_random_randint", num_inputs=0, differentiable=False,
          mutates_rng=True, aliases=["random_randint"])
def _random_randint(*, low: int = 0, high: int = 1, shape=None,
                    dtype: str = "int32", ctx: str = ""):
    return jax.random.randint(next_key(), _shape(shape), low, high,
                              dtype=jnp.dtype(dtype))


@register("_random_negative_binomial", num_inputs=0, differentiable=False,
          mutates_rng=True, aliases=["random_negative_binomial"])
def _random_negative_binomial(*, k: int = 1, p: float = 1.0, shape=None,
                              dtype: str = "float32", ctx: str = ""):
    lam = jax.random.gamma(next_key(), float(k), _shape(shape)) * (1 - p) / p
    return jax.random.poisson(next_key(), lam,
                              _shape(shape)).astype(jnp.dtype(dtype))


@register("_sample_multinomial", differentiable=False, mutates_rng=True,
          aliases=["sample_multinomial"])
def _sample_multinomial(data, *, shape=None, get_prob: bool = False,
                        dtype: str = "int32"):
    """Categorical draw from probability rows (reference:
    random/multisample_op.cc)."""
    n = 1 if shape is None else int(jnp.prod(jnp.asarray(_shape(shape))))
    logits = jnp.log(jnp.maximum(data, 1e-30))
    out_shape = _shape(shape)
    draws = jax.random.categorical(
        next_key(), logits, axis=-1,
        shape=(out_shape + data.shape[:-1]) if out_shape else data.shape[:-1])
    if out_shape:
        draws = jnp.moveaxis(draws, tuple(range(len(out_shape))),
                             tuple(range(-len(out_shape), 0)))
    return draws.astype(jnp.dtype(dtype))


@register("_shuffle", differentiable=False, mutates_rng=True,
          aliases=["shuffle"])
def _shuffle(data):
    return jax.random.permutation(next_key(), data, axis=0)


@register("_sample_unique_zipfian", num_inputs=0, differentiable=False,
          mutates_rng=True)
def _sample_unique_zipfian(*, range_max: int = 1, shape=None):
    n = _shape(shape)
    u = jax.random.uniform(next_key(), n)
    out = jnp.exp(u * jnp.log(float(range_max))).astype(jnp.int32) - 1
    return jnp.clip(out, 0, range_max - 1)


# per-element distribution-parameter samplers (sample_uniform etc.)
@register("sample_uniform", num_inputs=2, differentiable=False,
          mutates_rng=True)
def sample_uniform(low, high, *, shape=None, dtype: str = "float32"):
    s = _shape(shape)
    u = jax.random.uniform(next_key(), low.shape + s, dtype=jnp.dtype(dtype))
    return low.reshape(low.shape + (1,) * len(s)) + u * (
        high - low).reshape(low.shape + (1,) * len(s))


@register("sample_normal", num_inputs=2, differentiable=False,
          mutates_rng=True)
def sample_normal(mu, sigma, *, shape=None, dtype: str = "float32"):
    s = _shape(shape)
    z = jax.random.normal(next_key(), mu.shape + s, dtype=jnp.dtype(dtype))
    return mu.reshape(mu.shape + (1,) * len(s)) + z * sigma.reshape(
        sigma.shape + (1,) * len(s))


# ---------------------------------------------------------------------------
# python-level convenience API (mx.random / mx.nd.random)
# ---------------------------------------------------------------------------

def uniform(low=0.0, high=1.0, shape=None, dtype="float32", ctx=None, out=None):
    from .ndarray import invoke_by_name
    return invoke_by_name("_random_uniform", [], dict(
        low=float(low), high=float(high), shape=shape, dtype=dtype), out=out)


def normal(loc=0.0, scale=1.0, shape=None, dtype="float32", ctx=None, out=None):
    from .ndarray import invoke_by_name
    return invoke_by_name("_random_normal", [], dict(
        loc=float(loc), scale=float(scale), shape=shape, dtype=dtype), out=out)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None):
    from .ndarray import invoke_by_name
    return invoke_by_name("_random_randint", [], dict(
        low=int(low), high=int(high), shape=shape, dtype=dtype), out=out)


def randn(*shape, loc=0.0, scale=1.0, dtype="float32", ctx=None):
    return normal(loc, scale, shape, dtype, ctx)
