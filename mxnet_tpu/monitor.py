"""Per-batch tensor monitor (reference: ``python/mxnet/monitor.py``).

The reference ``mx.monitor.Monitor`` hooks an executor's per-op outputs
and stats weights on every ``interval``-th batch — the standard tool for
catching NaNs/blowups mid-training.  Here the same ``tic``/``toc``/
``toc_print`` API covers all three frontends:

- **Gluon**: ``install(block)`` registers forward hooks on every
  sub-block, so activations are statted as they are produced;
- **Module**: ``install(module)`` (or passing ``monitor=`` to
  ``Module.fit``) stats the bound executor's args/grads/outputs at
  ``toc`` time;
- **Executor**: ``install(executor)`` stats ``arg_dict``/``grad_dict``/
  ``outputs`` directly.

Stats are computed eagerly at capture time (the default stat is
``||x||_2 / sqrt(x.size)``), which forces the monitored arrays to
materialize — per-batch tensor inspection is inherently a synchronizing
debug tool; expect it to serialize the async pipeline while active.

Usage::

    mon = mx.monitor.Monitor(interval=10, pattern=".*weight.*")
    mon.install(net)
    for batch in loader:
        mon.tic()
        ...forward/backward/step...
        mon.toc_print()
"""
from __future__ import annotations

import logging
import math
import re

import numpy as np

from .base import MXNetError

__all__ = ["Monitor"]

_LOG = logging.getLogger("mxnet_tpu")


def _to_numpy(x):
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return np.asarray(x)


def _is_traced(x) -> bool:
    """True when ``x`` is an NDArray wrapping a JAX tracer — i.e. we are
    inside a hybridize/CachedOp trace, where values are symbolic and
    reading them would poison the array's engine var.  Hooks skip these:
    on a hybridized block, per-layer output stats exist only for the
    non-traced path; weights/grads are still statted at ``toc()``."""
    data = getattr(x, "_data", None)
    if data is None:
        return False
    try:
        import jax
        return isinstance(data, jax.core.Tracer)
    except Exception:       # noqa: BLE001 — jax internals moved
        return not hasattr(data, "block_until_ready") and \
            not isinstance(data, np.ndarray)


def default_stat(arr) -> float:
    """``||x||_2 / sqrt(x.size)`` (the reference's default stat_func) —
    scale-invariant enough to eyeball across layers, and NaN-propagating
    so a poisoned tensor is immediately visible."""
    a = _to_numpy(arr)
    if a.size == 0:
        return 0.0
    return float(np.linalg.norm(a.astype(np.float64)) / math.sqrt(a.size))


class Monitor:
    """reference: mx.monitor.Monitor(interval, stat_func, pattern, sort)."""

    def __init__(self, interval=1, stat_func=None, pattern=".*",
                 sort=False, monitor_all=False):
        if interval < 1:
            raise MXNetError("Monitor: interval must be >= 1")
        self.interval = int(interval)
        self.stat_func = stat_func or default_stat
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all
        self.activated = False
        self.step = 0
        self.queue = []             # (step, name, stat)
        self._blocks = []
        self._modules = []
        self._executors = []
        self._hooked = []       # (block, hook) pairs for uninstall()

    # ------------------------------------------------------------- install
    def install(self, target):
        """Attach to a Gluon ``Block``, a ``Module``, or an ``Executor``.
        May be called multiple times to monitor several targets;
        re-installing the same target is a no-op (Module.fit installs on
        every call)."""
        from .gluon.block import Block
        if isinstance(target, Block):
            self._install_block(target)
        elif hasattr(target, "arg_dict") and hasattr(target, "outputs"):
            if not any(target is e for e in self._executors):
                self._executors.append(target)
        elif hasattr(target, "bind") and hasattr(target, "get_outputs"):
            if not any(target is m for m in self._modules):
                self._modules.append(target)
        else:
            raise MXNetError(
                f"Monitor.install: cannot monitor {type(target).__name__} "
                f"(expected Gluon Block, Module, or Executor)")
        return self

    def _install_block(self, root):
        if any(root is b for b in self._blocks):
            return              # already hooked: never double-register
        self._blocks.append(root)
        monitor = self

        def _hook(block, _inputs, outputs):
            if not monitor.activated:
                return
            outs = outputs if isinstance(outputs, (list, tuple)) \
                else (outputs,)
            for i, o in enumerate(outs):
                name = f"{block.name}_output{i}" if len(outs) > 1 \
                    else f"{block.name}_output"
                monitor._stat_one(name, o)

        for blk in root._iter_blocks():
            blk.register_forward_hook(_hook)
            self._hooked.append((blk, _hook))

    def uninstall(self):
        """Remove every forward hook this monitor registered and forget
        the monitored targets, so a per-run Monitor does not leave stale
        hook closures on long-lived blocks (and stays collectable)."""
        for blk, hook in self._hooked:
            try:
                blk._forward_hooks.remove(hook)
            except ValueError:
                pass
        self._hooked = []
        self._blocks = []
        self._modules = []
        self._executors = []
        return self

    # ------------------------------------------------------------ stepping
    def tic(self):
        """Activate collection if this batch hits the interval.  Call
        before the forward pass (reference: Monitor.tic)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """End the monitoring scope: stat weights/gradients of installed
        targets, deactivate, and return ``[(step, name, stat), ...]``."""
        if not self.activated:
            return []
        for blk in self._blocks:
            self._stat_params(blk.collect_params().items())
        for mod in self._modules:
            exe = getattr(mod, "_exec", None)
            if exe is not None:
                self._stat_executor(exe)
        for exe in self._executors:
            self._stat_executor(exe)
        self.activated = False
        res = sorted(self.queue, key=lambda kv: kv[1]) if self.sort \
            else list(self.queue)
        self.queue = []
        return res

    def toc_print(self):
        """``toc()`` + log one line per stat (reference: toc_print)."""
        res = self.toc()
        for step, name, value in res:
            _LOG.info("Batch: %7d %30s %s", step, name, value)
        return res

    # ------------------------------------------------------------ internals
    def _stat_one(self, name, arr):
        if not self.re_prog.match(name) or _is_traced(arr):
            return
        try:
            self.queue.append((self.step, name, self.stat_func(arr)))
        except Exception as e:      # noqa: BLE001 — lazy/husk arrays
            self.queue.append((self.step, name, f"<error: {e}>"))

    def _stat_params(self, items):
        for name, p in items:
            try:
                data = p.data()
            except Exception:       # noqa: BLE001 — uninitialized
                continue
            self._stat_one(name, data)
            if p.grad_req != "null":
                try:
                    self._stat_one(name + "_grad", p.grad())
                except Exception:   # noqa: BLE001 — no grad attached
                    pass

    def _stat_executor(self, exe):
        for name, arr in exe.arg_dict.items():
            self._stat_one(name, arr)
        for name, arr in exe.grad_dict.items():
            self._stat_one(name + "_grad", arr)
        if self.monitor_all:
            for name, arr in getattr(exe, "aux_dict", {}).items():
                self._stat_one(name, arr)
        for i, out in enumerate(getattr(exe, "outputs", []) or []):
            self._stat_one(f"output{i}", out)
