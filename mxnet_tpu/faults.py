"""Deterministic seeded fault injection for the serving stack
(docs/serving.md §8).

The serving/decode layers are deep but optimistic: a failed device
execute, a corrupt cache blob, or a stuck step loop must surface as a
*typed, bounded* failure, and the only way to prove that is to make the
failures happen on demand — reproducibly, in CI, on numpy fakes.  This
module is that chaos switch: a :class:`FaultPlan` maps named injection
points (threaded through ``deploy``, ``compile_cache``, the batcher,
the decode engine, and the page allocator) to one of four fault modes,
with seeded-RNG probability and after-N-calls triggers, so a 5%%
execute-fault chaos run replays byte-identically from its spec string.

Site catalogue: every injection point is **declared** via
:func:`declare_fault_site` at the bottom of this module — the single
source of truth for the tables in docs/serving.md §8 and
docs/training_resilience.md §2 (rendered by ``tools/gen_fault_docs.py
--check`` in CI) and for the ``fault-site-soundness`` mxlint pass,
which statically validates every ``inject()``/``check()`` site literal
and every ``MXNET_FAULTS`` spec pattern in tests/benches/CI against it
(a typo'd site silently never fires — a chaos test that tests
nothing).  Dynamic scopes (one site name per replica id) are declared
as templates with ``<placeholder>`` segments:
``replica.<rid>.heartbeat`` covers ``replica.r0.heartbeat``.  fnmatch
globs in plan specs match across the whole catalogue (``decode.*``
matches the engine, ``train.*`` the training plane,
``replica.<rid>.*`` one replica); :func:`FaultPlan.parse` warns on a
rule whose pattern can match no declared site.

Spec grammar (``MXNET_FAULTS``, or :func:`install` / :func:`plan`)::

    plan  := rule (';' rule)*
    rule  := site '=' mode (',' key '=' value)*
    site  := dotted injection-point name; fnmatch globs allowed
             ('serving.*' matches every serving-layer site)
    mode  := fail | delay | corrupt | stall
    keys  := p=<float>      fire probability per call (default 1.0)
             after=<int>    skip the first N calls of the site (0)
             times=<int>    fire at most N times (default unlimited)
             ms=<float>     delay duration (delay: 10ms, stall: 1000ms)
             seed=<int>     RNG seed component for this rule (0)

    MXNET_FAULTS='serving.execute=fail,p=0.05,seed=7;compile_cache.load=corrupt,times=1'

Modes: **fail** raises :class:`InjectedFault` (marked ``transient`` so
the serving retry policy treats it as retryable); **delay** and
**stall** sleep (stall defaults 100x longer — the stuck-worker shape
that deadline propagation must bound); **corrupt** mutates the value
passing through the injection point (bytes get a flipped byte, float
arrays a NaN) so checksum/validation layers downstream must catch it.

Contracts:

- **zero-cost when off**: :func:`inject` / :func:`check` test one
  module global against None and return — no parsing, no locks, no
  allocation on the fault-free path (mirrors the ``runtime_metrics``
  ``_ENABLED`` discipline).
- **every fired fault is observable**: counted per (site, mode) on the
  plan, mirrored into ``serving.faults{site,mode}`` when runtime
  metrics are on, and recorded as a zero-length ``fault.<mode>`` span
  in the active trace so a chaos run's flight-recorder dumps show
  exactly which faults a request absorbed.
- **deterministic**: each rule owns a ``random.Random`` seeded from
  (seed, site, mode); one plan spec -> one reproducible decision
  sequence per rule, independent of other rules.
"""
from __future__ import annotations

import fnmatch
import logging
import re as _re
import threading
import time

from .base import MXNetError, get_env

__all__ = ["FaultRule", "FaultPlan", "InjectedFault", "FaultSite",
           "declare_fault_site", "declared_sites",
           "pattern_matches_declared", "install",
           "clear", "active", "plan", "inject", "check", "counters"]

_LOG = logging.getLogger("mxnet_tpu")

_MODES = ("fail", "delay", "corrupt", "stall")
_DEFAULT_MS = {"delay": 10.0, "stall": 1000.0}


# ---------------------------------------------------------------------------
# declared-site registry (the single source of truth for injection points)
# ---------------------------------------------------------------------------
_SITE_SEGMENT = _re.compile(r"^(?:[a-z0-9_]+|<[a-z0-9_]+>)$")


class FaultSite:
    """One declared injection point.  ``name`` may carry
    ``<placeholder>`` segments for dynamic scopes
    (``replica.<rid>.heartbeat``); ``modes`` are the fault modes the
    site honors (``kv_cache.allocate`` is fail-only: exhaustion is a
    refusal, not an exception); ``plane``/``where``/``notes`` feed the
    generated doc tables (tools/gen_fault_docs.py)."""

    __slots__ = ("name", "modes", "plane", "where", "notes")

    def __init__(self, name, modes, plane, where, notes):
        self.name = name
        self.modes = tuple(modes)
        self.plane = plane
        self.where = where
        self.notes = notes

    def glob(self):
        """The site as an fnmatch glob: placeholders become ``*``."""
        return _re.sub(r"<[a-z0-9_]+>", "*", self.name)

    def __repr__(self):
        return f"FaultSite({self.name!r}, modes={self.modes})"


FAULT_SITES = {}


def declare_fault_site(name, modes=_MODES, *, plane="serving", where="",
                       notes=""):
    """Register one injection point (or ``<placeholder>`` template).
    Call sites (``inject``/``check``) and ``MXNET_FAULTS`` spec
    patterns are validated against this registry — statically by the
    ``fault-site-soundness`` mxlint pass, and at plan-parse time by the
    unmatched-pattern warning in :meth:`FaultPlan.parse`."""
    name = str(name)
    if not name or not all(_SITE_SEGMENT.match(seg)
                           for seg in name.split(".")):
        raise MXNetError(
            f"fault site {name!r}: expected dotted lowercase segments "
            f"(dynamic parts as <placeholder>), e.g. "
            f"'replica.<rid>.heartbeat'")
    bad = [m for m in modes if m not in _MODES]
    if bad:
        raise MXNetError(
            f"fault site {name!r}: unknown mode(s) {bad} "
            f"(expected subset of {'/'.join(_MODES)})")
    # mxlint: disable=lock-discipline (contract: sites are declared at
    # import time — the module-bottom catalogue and plugin import
    # bodies — before any chaos plan can run; at runtime the registry
    # is read-only)
    FAULT_SITES[name] = FaultSite(name, modes, plane, where, notes)
    return name


def declared_sites():
    """{name: FaultSite} — the registry snapshot (doc generation,
    diagnose, tests)."""
    return dict(FAULT_SITES)


def _globs_intersect(a, b):
    """Whether two fnmatch globs can match a common string (``*`` any
    sequence, ``?``/``[...]`` any one char — the char-class
    overapproximation can only say "maybe" where the truth is "no",
    which keeps every consumer on the stay-quiet side)."""
    a = _re.sub(r"\[[^\]]*\]", "?", a)
    b = _re.sub(r"\[[^\]]*\]", "?", b)
    seen = set()
    stack = [(0, 0)]
    while stack:
        i, j = stack.pop()
        if (i, j) in seen:
            continue
        seen.add((i, j))
        if i == len(a) and j == len(b):
            return True
        if i < len(a) and a[i] == "*":
            stack.append((i + 1, j))            # * matches empty
            if j < len(b):
                stack.append((i, j + 1))        # * absorbs one char of b
            continue
        if j < len(b) and b[j] == "*":
            stack.append((i, j + 1))
            if i < len(a):
                stack.append((i + 1, j))
            continue
        if i < len(a) and j < len(b) \
                and (a[i] == "?" or b[j] == "?" or a[i] == b[j]):
            stack.append((i + 1, j + 1))
    return False


def pattern_matches_declared(pattern, mode=None):
    """Whether an fnmatch site ``pattern`` can match at least one
    declared site (template placeholders wild) — and, with ``mode``,
    one that honors that mode.  A pattern failing this is a chaos rule
    that can never fire."""
    pattern = str(pattern)
    if "<" in pattern or ">" in pattern:
        # a copy-pasted template name ("replica.<rid>.heartbeat"): the
        # literal placeholder never fnmatches a runtime site, but glob
        # intersection against the template would wave it through —
        # the site-name grammar forbids angle brackets, so reject here
        return False
    for site in FAULT_SITES.values():
        if _globs_intersect(str(pattern), site.glob()) \
                and (mode is None or mode in site.modes):
            return True
    return False


class InjectedFault(MXNetError):
    """A fault fired by the active :class:`FaultPlan`.

    ``transient`` marks it retryable to the serving retry policy — an
    injected execute failure models a transient device fault, which is
    exactly what bounded retries exist to absorb.  ``site``/``mode``
    let tests and the flight recorder attribute the failure."""

    transient = True

    def __init__(self, site, mode="fail"):
        self.site = site
        self.mode = mode
        super().__init__(f"injected fault at {site!r} (mode={mode})")


class FaultRule:
    """One ``site=mode,...`` clause of a plan.  Trigger state (calls
    seen, times fired, RNG) is mutated only under the owning plan's
    lock."""

    __slots__ = ("pattern", "mode", "p", "after", "times", "ms", "seed",
                 "calls", "fired", "_rng")

    def __init__(self, pattern, mode, p=1.0, after=0, times=None,
                 ms=None, seed=0):
        if mode not in _MODES:
            raise MXNetError(
                f"fault rule {pattern!r}: unknown mode {mode!r} "
                f"(expected one of {'/'.join(_MODES)})")
        if not 0.0 <= p <= 1.0:
            raise MXNetError(
                f"fault rule {pattern!r}: p={p} outside [0, 1]")
        if after < 0 or (times is not None and times < 1):
            raise MXNetError(
                f"fault rule {pattern!r}: after must be >= 0 and "
                f"times >= 1 (got after={after}, times={times})")
        self.pattern = pattern
        self.mode = mode
        self.p = float(p)
        self.after = int(after)
        self.times = None if times is None else int(times)
        self.ms = _DEFAULT_MS.get(mode, 0.0) if ms is None else float(ms)
        self.seed = int(seed)
        self.calls = 0
        self.fired = 0
        # per-rule deterministic stream: the decision sequence depends
        # only on (seed, pattern, mode) and this rule's own call order,
        # never on other rules or global RNG state
        import random
        self._rng = random.Random(f"{self.seed}\x1f{pattern}\x1f{mode}")

    def matches(self, site):
        return self.pattern == site or fnmatch.fnmatchcase(site,
                                                           self.pattern)

    def should_fire(self):
        # mxlint: disable=lock-discipline (contract: FaultPlan calls
        # this under its plan lock — rules are plan-internal state)
        self.calls += 1
        if self.calls <= self.after:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.p < 1.0 and self._rng.random() >= self.p:
            return False
        self.fired += 1
        return True

    def spec(self):
        out = f"{self.pattern}={self.mode}"
        if self.p < 1.0:
            out += f",p={self.p}"
        if self.after:
            out += f",after={self.after}"
        if self.times is not None:
            out += f",times={self.times}"
        if self.ms != _DEFAULT_MS.get(self.mode, 0.0):
            out += f",ms={self.ms}"
        if self.seed:
            out += f",seed={self.seed}"
        return out

    def __repr__(self):
        return (f"FaultRule({self.spec()!r}, calls={self.calls}, "
                f"fired={self.fired})")


def _parse_rule(clause):
    head, _, tail = clause.partition(",")
    site, sep, mode = head.partition("=")
    if not sep or not site or not mode:
        raise MXNetError(
            f"fault spec clause {clause!r}: expected 'site=mode[,k=v...]'"
            f" (grammar in mxnet_tpu/faults.py)")
    kw = {}
    if tail:
        for pair in tail.split(","):
            key, sep, value = pair.partition("=")
            key = key.strip()
            if not sep or key not in ("p", "after", "times", "ms",
                                      "seed"):
                raise MXNetError(
                    f"fault spec clause {clause!r}: bad option {pair!r} "
                    f"(expected p/after/times/ms/seed = value)")
            typ = float if key in ("p", "ms") else int
            try:
                kw[key] = typ(value)
            except ValueError as e:
                raise MXNetError(
                    f"fault spec clause {clause!r}: {e}") from None
    return FaultRule(site.strip(), mode.strip(), **kw)


class FaultPlan:
    """A parsed set of :class:`FaultRule`\\ s plus their firing state.

    The plan owns one lock for trigger bookkeeping; the sleep of a
    delay/stall fault happens OUTSIDE it so a stalled site never blocks
    other sites' trigger decisions."""

    def __init__(self, rules, spec=""):
        from . import engine
        self.rules = list(rules)
        self.spec = spec or ";".join(r.spec() for r in self.rules)
        self._lock = engine.make_lock("faults.FaultPlan._lock")

    @classmethod
    def parse(cls, spec):
        clauses = [c.strip() for c in str(spec).split(";") if c.strip()]
        if not clauses:
            raise MXNetError(
                f"fault spec {spec!r} holds no rules — expected "
                f"'site=mode[,k=v...][;...]'")
        rules = [_parse_rule(c) for c in clauses]
        # the PR-11 bug class: a typo'd site/pattern silently never
        # fires, and the chaos run "passes" while testing nothing.  A
        # warning (not an error): faults are a test harness, and the
        # registry must never make the harness itself the failure.
        for r in rules:
            if not pattern_matches_declared(r.pattern):
                _LOG.warning(
                    "faults: rule %r matches no declared fault site — "
                    "it can never fire (catalogue: "
                    "faults.declared_sites(), docs/serving.md §8)",
                    r.spec())
            elif not pattern_matches_declared(r.pattern, mode=r.mode):
                _LOG.warning(
                    "faults: rule %r: no site matching %r honors mode "
                    "%r — it can never fire", r.spec(), r.pattern,
                    r.mode)
        return cls(rules, spec=str(spec))

    # ------------------------------------------------------------- firing
    def fire(self, site, modes=None):
        """The first matching rule that fires for this call of ``site``
        (or None).  Every matching rule's call counter advances, so
        ``after=N`` counts real traffic even when an earlier rule
        shadows it.  ``modes`` restricts which rule modes may fire —
        sites with custom failure semantics (the page allocator's
        refusal contract) only honor the modes they can express; a
        non-matching mode neither fires nor consumes the rule's
        call/times budget at this site."""
        hit = None
        with self._lock:
            for rule in self.rules:
                if not rule.matches(site):
                    continue
                if modes is not None and rule.mode not in modes:
                    continue
                if rule.should_fire() and hit is None:
                    hit = rule
        if hit is not None:
            self._observe(site, hit)
        return hit

    def _observe(self, site, rule):
        from . import runtime_metrics as _rm, tracing as _tr
        if _rm._ENABLED:
            _rm.SERVING_FAULTS.inc(site=site, mode=rule.mode)
        if _tr._ENABLED:
            ctx = _tr.current_context()
            if ctx is not None:
                now = time.perf_counter()
                _tr.record_span(f"fault.{rule.mode}", ctx, now, now,
                                {"site": site})
        _LOG.debug("faults: fired %s at %s (rule %s)", rule.mode, site,
                   rule.spec())

    # ------------------------------------------------------------ readers
    def counters(self):
        """{'site-pattern:mode': fired} — what actually happened, for
        chaos-smoke assertions and incident dumps.  Multiple rules
        sharing a pattern+mode (staged kills: two ``after=N`` clauses
        on one site) aggregate into one entry."""
        with self._lock:
            out = {}
            for r in self.rules:
                key = f"{r.pattern}:{r.mode}"
                out[key] = out.get(key, 0) + r.fired
            return out

    def debug_state(self):
        with self._lock:
            return {"spec": self.spec,
                    "rules": [{"pattern": r.pattern, "mode": r.mode,
                               "p": r.p, "after": r.after,
                               "times": r.times, "ms": r.ms,
                               "seed": r.seed, "calls": r.calls,
                               "fired": r.fired}
                              for r in self.rules]}

    def __repr__(self):
        return f"FaultPlan({self.spec!r})"


# ---------------------------------------------------------------------------
# module-level switch (the hot path reads ONE global against None)
# ---------------------------------------------------------------------------
_ACTIVE = None


def _init_from_env():
    spec = get_env("MXNET_FAULTS", typ=str)
    if not spec:
        return None
    try:
        return FaultPlan.parse(spec)
    except MXNetError as e:
        # a typo in the chaos knob must not take the process down —
        # faults are a test harness, not a correctness dependency
        _LOG.warning("faults: ignoring invalid MXNET_FAULTS: %s", e)
        return None


def install(plan_or_spec):
    """Activate a plan process-wide (replacing any active one).
    Accepts a :class:`FaultPlan` or a spec string.  Returns the plan."""
    global _ACTIVE
    fp = plan_or_spec if isinstance(plan_or_spec, FaultPlan) \
        else FaultPlan.parse(plan_or_spec)
    _ACTIVE = fp
    return fp


def clear():
    """Deactivate fault injection (back to the zero-cost path)."""
    global _ACTIVE
    _ACTIVE = None


def active():
    """The installed :class:`FaultPlan`, or None."""
    return _ACTIVE


class plan:
    """Scoped installation for tests::

        with faults.plan("serving.execute=fail,times=1"):
            ...
    """

    def __init__(self, plan_or_spec):
        self._plan = plan_or_spec

    def __enter__(self):
        self._prev = _ACTIVE
        return install(self._plan)

    def __exit__(self, *exc):
        global _ACTIVE
        _ACTIVE = self._prev
        return False


def counters():
    """The active plan's fired counters ({} when off) — merged into
    flight-recorder incident dumps by ``tracing.record_incident``."""
    fp = _ACTIVE
    return fp.counters() if fp is not None else {}


# ---------------------------------------------------------------------------
# injection points
# ---------------------------------------------------------------------------
def _flip_byte(data):
    if not data:
        return data
    buf = bytearray(data)
    buf[len(buf) // 2] ^= 0xFF
    return bytes(buf)


def _corrupt_value(site, value):
    import numpy as np
    if value is None:
        # nothing flows through this site — the honest degraded
        # behavior is a typed failure, not silent success
        raise InjectedFault(site, "corrupt")
    if isinstance(value, (bytes, bytearray)):
        return _flip_byte(value)
    arr = np.array(value, copy=True)
    if arr.dtype.kind == "f" and arr.size:
        arr.flat[arr.size // 2] = np.nan
    elif arr.size:
        arr.flat[arr.size // 2] = ~arr.flat[arr.size // 2]
    return arr


def inject(site, value=None):
    """The generic injection point.  Zero-cost no-op without an active
    plan; otherwise applies the first firing rule for ``site``:

    - ``fail``    -> raises :class:`InjectedFault` (transient);
    - ``delay`` / ``stall`` -> sleeps the rule's ``ms``;
    - ``corrupt`` -> returns a corrupted copy of ``value`` (bytes: one
      flipped byte; float array: one NaN; ``value=None``: raises).

    Returns ``value`` (possibly corrupted) so call sites can thread a
    payload through: ``raw = faults.inject("compile_cache.load", raw)``.
    """
    fp = _ACTIVE
    if fp is None:
        return value
    rule = fp.fire(site)
    if rule is None:
        return value
    if rule.mode == "fail":
        raise InjectedFault(site)
    if rule.mode == "corrupt":
        return _corrupt_value(site, value)
    # mxlint: disable=deadline-soundness (contract: this sleep IS the
    # injected delay/stall fault — the unbounded hang under test that
    # the runtime deadline machinery must bound from the outside)
    time.sleep(rule.ms / 1e3)           # delay | stall
    return value


def check(site):
    """Fire-only probe for sites with custom failure semantics (the
    page allocator reports exhaustion by returning False, not by
    raising).  True when a ``fail``-mode rule fired for ``site``;
    never raises, never sleeps — and only ``fail`` rules fire here,
    so a latency-only plan (``*=delay``) can never masquerade as
    resource exhaustion."""
    fp = _ACTIVE
    if fp is None:
        return False
    return fp.fire(site, modes=("fail",)) is not None


# ---------------------------------------------------------------------------
# the declared-site catalogue (tools/gen_fault_docs.py renders this into
# docs/serving.md §8 and docs/training_resilience.md §2; the
# fault-site-soundness mxlint pass validates every call site and spec
# pattern against it).  Declared BEFORE the env plan parses so a typo'd
# MXNET_FAULTS pattern warns at import.
# ---------------------------------------------------------------------------
declare_fault_site(
    "serving.execute", where="DynamicBatcher.run_batch device execute",
    notes="what the serving retry + bisection + deadline machinery "
          "absorbs")
declare_fault_site(
    "serving.compile", where="DynamicBatcher.program_for bucket build",
    notes="transient build failure; waiters hand the build to a "
          "retrier, `stall` is the wedged-builder shape the deadline "
          "bound covers")
declare_fault_site(
    "deploy.execute", where="StableHLOModel.call direct artifact call")
declare_fault_site(
    "compile_cache.load", where="persistent compile-cache blob read",
    notes="`corrupt` flips a byte so the checksum tier must catch it; "
          "all modes degrade to a counted miss — the cache never "
          "raises")
declare_fault_site(
    "repository.load_artifact", where="ModelRepository deploy-path pull")
declare_fault_site(
    "decode.prefill", where="DecodeEngine prefill model call")
declare_fault_site(
    "decode.step", where="DecodeEngine fixed-batch decode step")
declare_fault_site(
    "decode.verify", where="speculative verification (target model)",
    notes="failure bisects, then quarantines the poisoned sequence "
          "through the §8 path")
declare_fault_site(
    "decode.prefix_lookup", where="prefix-cache radix lookup at "
                                  "admission",
    notes="degrades to a plain prefill, never wrong tokens; no value "
          "flows through, so `corrupt` raises like `fail`")
declare_fault_site(
    "kv_cache.allocate", modes=("fail",),
    where="PageAllocator page grant",
    notes="fail-only: injected pool exhaustion is a refusal, not an "
          "exception")
declare_fault_site(
    "replica.<rid>.execute", where="one replica's dispatch "
                                   "(docs/serving.md §10)",
    notes="kill ONE replica by id, or all at once via `replica.*`")
declare_fault_site(
    "replica.<rid>.heartbeat", where="one replica's beat loop",
    notes="`stall` is the wedged-worker shape siblings must detect")
declare_fault_site(
    "replica.<rid>.decode.prefill",
    where="a replica-owned decode engine's prefill")
declare_fault_site(
    "replica.<rid>.decode.step",
    where="a replica-owned decode engine's decode step")
declare_fault_site(
    "replica.<rid>.decode.verify",
    where="a replica-owned decode engine's speculative verify")
declare_fault_site(
    "replica.<rid>.decode.prefix_lookup",
    where="a replica-owned decode engine's prefix-cache lookup")
declare_fault_site(
    "autoscale.decide", modes=("fail", "delay"),
    where="Autoscaler actuation — fires before add/remove_replica "
          "(docs/serving.md §11)",
    notes="`fail` is the scale-up-whose-prewarm-dies shape: the loop "
          "must count an error decision, keep its target, and back "
          "off, never crash or staircase retries")
declare_fault_site(
    "admission.check", modes=("fail", "delay"),
    where="AdmissionController.check tenant gate (docs/serving.md "
          "§11)",
    notes="`fail` models a broken quota/tier lookup; admission "
          "errors are typed at the caller, never a hang — `delay` "
          "stresses the deadline budget at the earliest gate")

declare_fault_site(
    "train.step", plane="training",
    where="ShardedTrainer.step() entry "
          "(docs/training_resilience.md §2)",
    notes="`stall` is the wedged-collective shape the step watchdog "
          "must bound; `fail` the mid-step kill; `corrupt` raises "
          "(nothing flows through)")
declare_fault_site(
    "train.data.next", modes=("fail", "delay", "stall"), plane="training",
    where="every DataIter.next() batch handoff",
    notes="fires before the cursor advances — a killed fetch never "
          "half-consumes a batch")
declare_fault_site(
    "kvstore.push", modes=("fail", "delay", "stall"), plane="training",
    where="classic kvstore push tier",
    notes="covers gluon.Trainer's sync path")
declare_fault_site(
    "kvstore.pull", modes=("fail", "delay", "stall"), plane="training",
    where="classic kvstore pull tier")
declare_fault_site(
    "kvstore.pushpull", modes=("fail", "delay", "stall"), plane="training",
    where="fused XLA collective launch (kvstore('xla'))",
    notes="one bucketed allreduce = one site")
declare_fault_site(
    "checkpoint.save", plane="training",
    where="CheckpointManager.save; the durability barrier (corrupt)",
    notes="`corrupt` bit-flips one byte of the just-verified step's "
          "payload — post-barrier silent rot the integrity manifest "
          "must detect, never load")
declare_fault_site(
    "checkpoint.restore", plane="training",
    where="CheckpointManager.restore",
    notes="`corrupt` flips the candidate payload before it is read, "
          "forcing the verified-step fallback")

_ACTIVE = _init_from_env()
