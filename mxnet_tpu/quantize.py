"""Blockwise int8/fp8 quantization core — move fewer bytes everywhere.

ONE quantization algebra shared by every byte-moving surface:

- **gradient collectives** — ``kvstore`` push/pull and the fused XLA
  pushpull quantize *inside* the jitted collective (EQuARX, PAPERS.md),
  so only int8/fp8 payloads plus per-block f32 scales cross chips;
- **ShardedTrainer** — the data-parallel gradient allreduce runs the
  same quant/all-gather/dequant body under ``shard_map``;
- **serving export** — ``deploy.export_stablehlo(quantize=...)`` bakes
  int8/fp8 weights + per-tensor scales into the artifact (weight-only
  post-training quantization, the Gemma-on-TPU serving shape).

Numerical contract (the reason the dtype-promotion lint pass exempts
this file's core): quantized payloads are ALWAYS accumulated in
float32 — ``dequantize`` widens the int8/fp8 payload to f32, applies
the scale in f32, sums across devices in f32, and only then casts back
to the caller's dtype.  Narrowing happens exactly once, at the
quantize boundary, where the per-block scale bounds the error to
``amax / qmax`` per element; the **error-feedback residual** carries
that rounding error into the next step so it cancels in expectation
(gradient compression stays convergent — EQuARX / 1-bit-SGD lineage).

Everything here is pure ``jnp`` and jit-safe: no host syncs, no python
branching on traced values, so XLA fuses quant/dequant into the
surrounding collective program.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .base import MXNetError, get_env

__all__ = [
    "CompressionSpec", "quantize", "dequantize",
    "quantize_with_feedback", "allreduce_sum", "allreduce_mean",
    "wire_bytes", "logical_bytes", "quantize_tensor",
    "dequantize_tensor", "tensor_scale",
]

# int8 uses the symmetric range [-127, 127] (−128 is never emitted so
# the codebook is symmetric and dequant needs no zero-point); fp8
# e4m3fn saturates at ±448.
_QMAX = {"int8": 127.0, "fp8": 448.0}
_WIRE_ITEMSIZE = {"int8": 1, "fp8": 1}      # both are 1-byte payloads
_SCALE_ITEMSIZE = 4                          # per-block f32 scale


class CompressionSpec:
    """Immutable description of one quantized-transport policy.

    - ``kind``: ``'int8'`` (symmetric codebook, round-to-nearest or
      stochastic) or ``'fp8'`` (float8_e4m3fn payload; rounding is the
      fp8 cast itself).
    - ``block``: elements per scale block.  Smaller blocks track local
      gradient magnitude better (lower error) at a scale-overhead cost
      of ``4 / block`` bytes per element.
    - ``stochastic``: int8 rounds stochastically (unbiased: E[q] = x)
      instead of to-nearest; needs a PRNG ``key`` at quantize time.
    - ``error_feedback``: carry the per-device rounding error into the
      next step's gradient (on by default — this is what preserves
      convergence for gradient compression).
    """

    __slots__ = ("kind", "block", "stochastic", "error_feedback")

    def __init__(self, kind="int8", block=128, stochastic=False,
                 error_feedback=True):
        if kind not in _QMAX:
            raise MXNetError(
                f"CompressionSpec: unknown kind {kind!r} "
                f"(supported: {sorted(_QMAX)})")
        if kind == "fp8" and stochastic:
            raise MXNetError(
                "CompressionSpec: stochastic rounding is int8-only — "
                "the fp8 payload rounds in the e4m3 cast itself "
                "(round-to-nearest-even); silently ignoring the knob "
                "would hand back biased rounding where unbiased was "
                "asked for")
        block = int(block)
        if block < 1:
            raise MXNetError(
                f"CompressionSpec: block must be >= 1, got {block}")
        object.__setattr__(self, "kind", kind)
        object.__setattr__(self, "block", block)
        object.__setattr__(self, "stochastic", bool(stochastic))
        object.__setattr__(self, "error_feedback", bool(error_feedback))

    def __setattr__(self, name, value):
        raise AttributeError("CompressionSpec is immutable")

    # ------------------------------------------------------------ parsing
    @classmethod
    def parse(cls, value):
        """``None`` | spec | ``'int8'`` | ``'int8:block=64,stochastic=1'``
        | ``{'type': 'int8', 'block': 64, ...}`` -> CompressionSpec|None.
        """
        if value is None:
            return None
        if isinstance(value, cls):
            return value
        if isinstance(value, str):
            text = value.strip()
            if not text or text.lower() == "none":
                return None
            kind, _, opts = text.partition(":")
            params = {"type": kind.strip()}
            for item in filter(None, opts.split(",")):
                k, sep, v = item.partition("=")
                if not sep:
                    raise MXNetError(
                        f"CompressionSpec: malformed option {item!r} in "
                        f"{value!r} (want key=value)")
                params[k.strip()] = v.strip()
            value = params
        if not isinstance(value, dict):
            raise MXNetError(
                f"CompressionSpec: cannot parse {value!r}")
        params = dict(value)
        kind = params.pop("type", params.pop("kind", "int8"))
        known = {"block", "stochastic", "error_feedback"}
        unknown = set(params) - known
        if unknown:
            raise MXNetError(
                f"CompressionSpec: unknown params {sorted(unknown)} "
                f"(known: {sorted(known)})")

        def as_bool(v):
            if isinstance(v, str):
                return v.strip().lower() not in ("0", "false", "no", "")
            return bool(v)

        return cls(kind=kind,
                   block=params.get("block", 128),
                   stochastic=as_bool(params.get("stochastic", False)),
                   error_feedback=as_bool(
                       params.get("error_feedback", True)))

    @classmethod
    def from_env(cls):
        """The ``MXNET_KVSTORE_GRAD_COMPRESSION`` default (None when
        unset)."""
        return cls.parse(get_env("MXNET_KVSTORE_GRAD_COMPRESSION"))

    # ---------------------------------------------------------- properties
    @property
    def qmax(self) -> float:
        return _QMAX[self.kind]

    @property
    def wire_dtype(self):
        return jnp.int8 if self.kind == "int8" else jnp.float8_e4m3fn

    def key(self):
        """Hashable identity for program caches."""
        return (self.kind, self.block, self.stochastic,
                self.error_feedback)

    def __repr__(self):
        return (f"CompressionSpec({self.kind!r}, block={self.block}, "
                f"stochastic={self.stochastic}, "
                f"error_feedback={self.error_feedback})")

    def __eq__(self, other):
        return isinstance(other, CompressionSpec) \
            and self.key() == other.key()

    def __hash__(self):
        return hash(self.key())


# ------------------------------------------------------------------ sizing
def _nblocks(n_elems: int, spec: CompressionSpec) -> int:
    return max(1, math.ceil(n_elems / spec.block))


def wire_bytes(n_elems: int, spec: CompressionSpec) -> int:
    """Bytes of the compressed representation one device transmits for
    an ``n_elems`` tensor: the (block-padded) 1-byte payload plus one
    f32 scale per block."""
    nb = _nblocks(n_elems, spec)
    return nb * spec.block * _WIRE_ITEMSIZE[spec.kind] \
        + nb * _SCALE_ITEMSIZE


def logical_bytes(n_elems: int, dtype) -> int:
    """Uncompressed payload size (what the f32 collective would move)."""
    return int(n_elems) * jnp.dtype(dtype).itemsize


# -------------------------------------------------------------- quant core
def _blockify(x, spec: CompressionSpec):
    """Flatten + zero-pad to a block multiple -> (nb, block) f32."""
    flat = jnp.ravel(x).astype(jnp.float32)
    n = flat.shape[0]
    nb = _nblocks(n, spec)
    pad = nb * spec.block - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    return flat.reshape(nb, spec.block)


def quantize(x, spec: CompressionSpec, key=None):
    """Blockwise quantize ``x`` -> ``(payload, scales)``.

    ``payload`` is ``(nb, block)`` of ``spec.wire_dtype``; ``scales``
    is ``(nb,)`` float32 with ``x ~= payload * scales[:, None]``.
    Stochastic int8 rounding needs ``key`` (a jax PRNG key); it is
    unbiased per element, so quantization noise averages out across
    devices/steps even without error feedback.
    """
    blocks = _blockify(x, spec)
    amax = jnp.max(jnp.abs(blocks), axis=1)
    # all-zero blocks quantize through scale 1 (payload is all zeros
    # either way; guards the 0/0 in the divide)
    scales = jnp.where(amax > 0.0, amax / spec.qmax, 1.0)
    y = blocks / scales[:, None]
    if spec.kind == "int8":
        if spec.stochastic:
            if key is None:
                raise MXNetError(
                    "quantize: stochastic rounding needs a PRNG key")
            # floor(y + u), u ~ U[0,1): rounds x up with probability
            # frac(x) — the unbiased-rounding identity E[q] = y
            u = jax.random.uniform(key, y.shape, jnp.float32)
            q = jnp.floor(y + u)
        else:
            q = jnp.round(y)
        payload = jnp.clip(q, -spec.qmax, spec.qmax).astype(jnp.int8)
    else:
        # fp8: the e4m3 cast IS the rounding step (round-to-nearest-even
        # in hardware); y is pre-scaled into the saturating range
        payload = y.astype(jnp.float8_e4m3fn)
    return payload, scales


def dequantize(payload, scales, shape, dtype, n_elems=None):
    """Invert :func:`quantize` back to ``shape``/``dtype``.

    The widen-multiply runs in float32 regardless of payload or target
    dtype (the accumulate-wide contract in the module docstring).
    """
    f = payload.astype(jnp.float32) * scales[:, None]
    flat = f.reshape(-1)
    n = n_elems
    if n is None:
        n = 1
        for d in shape:
            n *= int(d)
    return flat[:n].reshape(shape).astype(dtype)


def quantize_with_feedback(grad, residual, spec: CompressionSpec,
                           key=None):
    """Error-feedback quantize: ``(payload, scales, new_residual)``.

    The residual (previous steps' rounding error, f32, same shape as
    ``grad``) is added before quantizing; the new residual is what THIS
    quantization failed to represent.  With ``spec.error_feedback``
    off, the residual passes through as zeros.
    """
    g32 = grad.astype(jnp.float32)
    total = g32 + residual if spec.error_feedback else g32
    payload, scales = quantize(total, spec, key=key)
    if spec.error_feedback:
        deq = dequantize(payload, scales, total.shape, jnp.float32)
        new_residual = total - deq
    else:
        new_residual = jnp.zeros_like(residual)
    return payload, scales, new_residual


# ------------------------------------------------------- collective bodies
def allreduce_sum(x, residual, spec: CompressionSpec, axis_name,
                  key=None):
    """Quantized allreduce-sum for use INSIDE ``shard_map``: each
    device quantizes its local ``x`` (+ error-feedback ``residual``),
    all-gathers the compressed payload + scales over ``axis_name``
    (only compressed bytes cross the interconnect), dequantizes every
    device's contribution in f32, and sums.  Returns
    ``(summed, new_residual)`` — ``summed`` is replicated (identical on
    every device), ``new_residual`` stays per-device.
    """
    payload, scales, new_res = quantize_with_feedback(
        x, residual, spec, key=key)
    qg = lax.all_gather(payload, axis_name)          # (ndev, nb, block)
    sg = lax.all_gather(scales, axis_name)           # (ndev, nb)
    # accumulate across devices in f32 (see module docstring), then a
    # single narrowing cast back to the caller's dtype
    acc = jnp.sum(qg.astype(jnp.float32) * sg[:, :, None], axis=0)
    n = 1
    for d in x.shape:
        n *= int(d)
    out = acc.reshape(-1)[:n].reshape(x.shape).astype(x.dtype)
    return out, new_res


def allreduce_mean(x, residual, spec: CompressionSpec, axis_name,
                   key=None):
    """:func:`allreduce_sum` divided by the axis size (the
    data-parallel gradient mean)."""
    summed, new_res = allreduce_sum(x, residual, spec, axis_name,
                                    key=key)
    ndev = lax.psum(1, axis_name)
    return (summed.astype(jnp.float32) / ndev).astype(x.dtype), new_res


# -------------------------------------------------- per-tensor (serving)
def tensor_scale(w, spec: CompressionSpec) -> float:
    """Per-tensor calibration scale (host-side, used at export time)."""
    import numpy as np
    amax = float(np.max(np.abs(np.asarray(w, dtype=np.float32))))
    return amax / spec.qmax if amax > 0.0 else 1.0


def quantize_tensor(w, scale: float, spec: CompressionSpec):
    """Whole-tensor quantize against a fixed scale (the serving-export
    path: ONE scale per weight tensor, recorded in the manifest)."""
    y = jnp.asarray(w, jnp.float32) / jnp.float32(scale)
    if spec.kind == "int8":
        return jnp.clip(jnp.round(y), -spec.qmax,
                        spec.qmax).astype(jnp.int8)
    return y.astype(jnp.float8_e4m3fn)


def dequantize_tensor(q, scale: float, dtype):
    """Widen a per-tensor quantized weight back (f32 multiply, single
    narrowing cast — same contract as :func:`dequantize`)."""
    return (q.astype(jnp.float32) * jnp.float32(scale)).astype(dtype)
