"""Training callbacks (reference: ``python/mxnet/callback.py``)."""
from __future__ import annotations

import logging
import time

from . import perf_account as _pa
from . import runtime_metrics as _rm

__all__ = ["Speedometer", "do_checkpoint", "ProgressBar",
           "LogValidationMetricsCallback", "module_checkpoint"]


def do_checkpoint(prefix, period=1):
    """Epoch-end callback saving symbol+params (reference: do_checkpoint)."""
    from .module.module import save_checkpoint
    period = int(max(1, period))

    def _callback(epoch, sym, arg_params, aux_params):
        if (epoch + 1) % period == 0:
            save_checkpoint(prefix, epoch, sym, arg_params, aux_params)
    return _callback


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    period = int(max(1, period))

    def _callback(epoch, sym=None, arg=None, aux=None):
        if (epoch + 1) % period == 0:
            mod.save_checkpoint(prefix, epoch, save_optimizer_states)
    return _callback


class Speedometer:
    """Log samples/sec every `frequent` batches (reference: Speedometer)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0
        self.last_count = 0

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / \
                    (time.time() - self.tic)
                # publish into the metrics registry so throughput shows
                # up in Prometheus/TensorBoard exports without extra
                # wiring (no-op while MXNET_RUNTIME_METRICS is off)
                _rm.TRAINER_SAMPLES_PER_SEC.set(speed)
                # step attribution, when any trainer published it:
                # windowed MFU + the current bottleneck verdict ride
                # the same log line as the throughput
                verdict = _pa.current_verdict()
                perf = ("" if verdict is None else
                        f" mfu={_pa.current_mfu():.3f} verdict={verdict}")
                if param.eval_metric is not None:
                    names, vals = param.eval_metric.get()
                    if not isinstance(names, list):
                        names, vals = [names], [vals]
                    msg = " ".join(f"{n}={v:.6f}" for n, v in
                                   zip(names, vals))
                    logging.info("Epoch[%d] Batch [%d] Speed: %.2f "
                                 "samples/sec %s%s", param.epoch, count,
                                 speed, msg, perf)
                    if self.auto_reset:
                        param.eval_metric.reset()
                else:
                    logging.info("Epoch[%d] Batch [%d] Speed: %.2f "
                                 "samples/sec%s", param.epoch, count,
                                 speed, perf)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class ProgressBar:
    """Text progress bar per epoch (reference: ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled = int(round(self.bar_len * count / float(self.total)))
        pct = round(100.0 * count / float(self.total), 1)
        bar = "=" * filled + "-" * (self.bar_len - filled)
        logging.info("[%s] %s%%", bar, pct)


class LogValidationMetricsCallback:
    """reference: LogValidationMetricsCallback."""

    def __call__(self, param):
        if param.eval_metric is None:
            return
        names, vals = param.eval_metric.get()
        if not isinstance(names, list):
            names, vals = [names], [vals]
        for name, value in zip(names, vals):
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                         value)
