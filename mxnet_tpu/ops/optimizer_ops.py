"""Fused optimizer-update operators.

Reference: ``src/operator/optimizer_op.cc`` (sgd_update, sgd_mom_update,
adam_update, lamb_update_*, ftrl_update, signum, rmsprop — SURVEY.md 2.1).

Purity note: the reference ops mutate weight/state in place; these are pure
functions returning the new weight *and* new state tensors (num_outputs > 1
where the reference mutated aux state).  ``mxnet_tpu.optimizer`` writes the
results back, and under the hybridized/pjit training path these fuse into
the step program so the distinction costs nothing — XLA buffer donation
gives the in-place behavior at the memory level.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _prep_grad(grad, rescale_grad, clip_gradient, wd, weight):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register("sgd_update", num_inputs=2)
def sgd_update(weight, grad, *, lr: float = 0.01, wd: float = 0.0,
               rescale_grad: float = 1.0, clip_gradient: float = -1.0,
               lazy_update: bool = True):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * g


@register("sgd_mom_update", num_inputs=3, num_outputs=2)
def sgd_mom_update(weight, grad, mom, *, lr: float = 0.01,
                   momentum: float = 0.0, wd: float = 0.0,
                   rescale_grad: float = 1.0, clip_gradient: float = -1.0,
                   lazy_update: bool = True):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    mom_new = momentum * mom - lr * g
    return weight + mom_new, mom_new


@register("nag_mom_update", num_inputs=3, num_outputs=2)
def nag_mom_update(weight, grad, mom, *, lr: float = 0.01,
                   momentum: float = 0.0, wd: float = 0.0,
                   rescale_grad: float = 1.0, clip_gradient: float = -1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    mom_new = momentum * mom + g
    return weight - lr * (g + momentum * mom_new), mom_new


@register("mp_sgd_update", num_inputs=3, num_outputs=2)
def mp_sgd_update(weight, grad, weight32, *, lr: float = 0.01, wd: float = 0.0,
                  rescale_grad: float = 1.0, clip_gradient: float = -1.0,
                  lazy_update: bool = True):
    """Multi-precision SGD: fp32 master weights (reference:
    optimizer_op.cc MP_SGD)."""
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient,
                   wd, weight32)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", num_inputs=4, num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, *, lr: float = 0.01,
                      momentum: float = 0.0, wd: float = 0.0,
                      rescale_grad: float = 1.0, clip_gradient: float = -1.0,
                      lazy_update: bool = True):
    g = _prep_grad(grad.astype(jnp.float32), rescale_grad, clip_gradient,
                   wd, weight32)
    mom_new = momentum * mom - lr * g
    w32 = weight32 + mom_new
    return w32.astype(weight.dtype), mom_new, w32


@register("adam_update", num_inputs=4, num_outputs=3)
def adam_update(weight, grad, mean, var, *, lr: float = 0.001,
                beta1: float = 0.9, beta2: float = 0.999,
                epsilon: float = 1e-8, wd: float = 0.0,
                rescale_grad: float = 1.0, clip_gradient: float = -1.0,
                lazy_update: bool = True):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - lr * mean_new / (jnp.sqrt(var_new) + epsilon)
    return w, mean_new, var_new


@register("adamw_update", num_inputs=5, num_outputs=3,
          aliases=["_adamw_update", "_contrib_adamw_update"])
def adamw_update(weight, grad, mean, var, rescale_grad_arr, *,
                 lr: float = 0.001, beta1: float = 0.9, beta2: float = 0.999,
                 epsilon: float = 1e-8, wd: float = 0.0, eta: float = 1.0,
                 clip_gradient: float = -1.0):
    """AdamW: decoupled weight decay (reference:
    src/operator/contrib/adamw.cc)."""
    g = grad * rescale_grad_arr
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - eta * (lr * mean_new / (jnp.sqrt(var_new) + epsilon)
                        + wd * weight)
    return w, mean_new, var_new


@register("lamb_update_phase1", num_inputs=4)
def lamb_update_phase1(weight, grad, mean, var, *, beta1: float = 0.9,
                       beta2: float = 0.999, epsilon: float = 1e-6,
                       t: int = 1, bias_correction: bool = True,
                       wd: float = 0.0, rescale_grad: float = 1.0,
                       clip_gradient: float = -1.0):
    """LAMB phase 1 (reference: optimizer_op.cc lamb_update_phase1):
    returns the raw update direction g'.  NOTE: returns only the direction;
    phase-1 state updates come from the same formula and are recomputed by
    the optimizer wrapper via lamb_update_states for pure-function form."""
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    if bias_correction:
        mean_hat = mean_new / (1.0 - beta1 ** t)
        var_hat = var_new / (1.0 - beta2 ** t)
    else:
        mean_hat, var_hat = mean_new, var_new
    return mean_hat / (jnp.sqrt(var_hat) + epsilon) + wd * weight


@register("lamb_update_states", num_inputs=4, num_outputs=2)
def lamb_update_states(weight, grad, mean, var, *, beta1: float = 0.9,
                       beta2: float = 0.999, rescale_grad: float = 1.0,
                       clip_gradient: float = -1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return (beta1 * mean + (1 - beta1) * g,
            beta2 * var + (1 - beta2) * jnp.square(g))


@register("lamb_update_phase2", num_inputs=4)
def lamb_update_phase2(weight, g, r1, r2, *, lr: float = 0.01,
                       lower_bound: float = -1.0, upper_bound: float = -1.0):
    """LAMB phase 2: trust-ratio scaled step (reference: lamb_update_phase2)."""
    r1c = r1
    if lower_bound is not None and lower_bound > 0:
        r1c = jnp.maximum(r1c, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1c = jnp.minimum(r1c, upper_bound)
    ratio = jnp.where(jnp.logical_and(r1c > 0, r2 > 0), r1c / r2, 1.0)
    return weight - lr * ratio * g


@register("ftrl_update", num_inputs=4, num_outputs=3)
def ftrl_update(weight, grad, z, n, *, lr: float = 0.1, lamda1: float = 0.01,
                beta: float = 1.0, wd: float = 0.0, rescale_grad: float = 1.0,
                clip_gradient: float = -1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    n_new = n + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(z_new) > lamda1,
        -(z_new - jnp.sign(z_new) * lamda1)
        / ((beta + jnp.sqrt(n_new)) / lr + wd),
        0.0)
    return w, z_new, n_new


@register("rmsprop_update", num_inputs=3, num_outputs=2)
def rmsprop_update(weight, grad, n, *, lr: float = 0.001, gamma1: float = 0.95,
                   epsilon: float = 1e-8, wd: float = 0.0,
                   rescale_grad: float = 1.0, clip_gradient: float = -1.0,
                   clip_weights: float = -1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new


@register("rmspropalex_update", num_inputs=5, num_outputs=4)
def rmspropalex_update(weight, grad, n, g_acc, delta, *, lr: float = 0.001,
                       gamma1: float = 0.95, gamma2: float = 0.9,
                       epsilon: float = 1e-8, wd: float = 0.0,
                       rescale_grad: float = 1.0, clip_gradient: float = -1.0,
                       clip_weights: float = -1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
    g_new = gamma1 * g_acc + (1 - gamma1) * g
    # n - g_acc^2 >= 0 holds for states evolved from zero with one decay
    # rate (running E[g^2] >= (running E[g])^2), but nothing enforces it
    # for loaded/hand-built states — clamp so the sqrt can't NaN
    delta_new = gamma2 * delta - lr * g / jnp.sqrt(
        jnp.maximum(n_new - jnp.square(g_new), 0.0) + epsilon)
    w = weight + delta_new
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n_new, g_new, delta_new


@register("signsgd_update", num_inputs=2)
def signsgd_update(weight, grad, *, lr: float = 0.01, wd: float = 0.0,
                   rescale_grad: float = 1.0, clip_gradient: float = -1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    return weight - lr * jnp.sign(g)


@register("signum_update", num_inputs=3, num_outputs=2)
def signum_update(weight, grad, mom, *, lr: float = 0.01,
                  momentum: float = 0.0, wd: float = 0.0,
                  rescale_grad: float = 1.0, clip_gradient: float = -1.0,
                  wd_lh: float = 0.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient, wd, weight)
    mom_new = momentum * mom - (1 - momentum) * g
    w = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new)
    return w, mom_new


@register("_contrib_multi_lars", num_inputs=4, aliases=["multi_lars"])
def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, *, eta: float = 0.001,
               eps: float = 1e-8, rescale_grad: float = 1.0):
    """LARS learning-rate scaling over stacked norms (reference:
    contrib/multi_lars.cc)."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    trust = jnp.where(
        jnp.logical_and(w_norm > 0, g_norm > 0),
        eta * w_norm / (g_norm + wds * w_norm + eps), 1.0)
    return lrs * trust
