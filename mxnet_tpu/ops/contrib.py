"""Contrib operators — notably the transformer MultiHeadAttention kernels.

Reference: ``src/operator/contrib/transformer.cc``
(``_contrib_interleaved_matmul_selfatt_qk`` etc. — the MHA kernels named in
the north star), plus ROIAlign, AdaptiveAvgPooling2D, BilinearResize2D,
index ops (SURVEY.md 2.1).

TPU-native: the interleaved-matmul ops are thin einsum reshapes that XLA
maps onto batched MXU GEMMs; a fused Pallas flash-attention path backs the
same API for long sequences (ops/pallas_kernels.py supplies it and
gluon.contrib MultiHeadAttention selects it) — the reference's O(L^2)
materialized-scores semantics are preserved here for parity and for short L.

Layout contract (matches the reference ops):
  self-attention : qkv interleaved (L, B, H*3*D) — per head [q | k | v]
  enc-dec        : q (L_q, B, H*D), kv interleaved (L_kv, B, H*2*D)
  attention maps : (B*H, L_q, L_kv)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


@register("_contrib_div_sqrt_dim", aliases=["div_sqrt_dim"])
def div_sqrt_dim(data):
    """data / sqrt(last_dim) (reference: transformer.cc DivSqrtDim)."""
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], dtype=data.dtype))


def _split_interleaved(qkv, heads, n):
    """(L, B, H*n*D) -> n tensors of (B*H, L, D)."""
    L, B, HnD = qkv.shape
    D = HnD // (heads * n)
    x = qkv.reshape(L, B, heads, n, D)
    parts = [x[:, :, :, i, :] for i in range(n)]
    # (L, B, H, D) -> (B*H, L, D)
    return [p.transpose(1, 2, 0, 3).reshape(B * heads, L, D) for p in parts]


@register("_contrib_interleaved_matmul_selfatt_qk",
          aliases=["interleaved_matmul_selfatt_qk"])
def interleaved_matmul_selfatt_qk(queries_keys_values, *, heads: int = 1):
    """scores = (Q/sqrt(D)) @ K^T from interleaved qkv
    (reference: transformer.cc InterleavedMatMulSelfAttQK)."""
    q, k, _ = _split_interleaved(queries_keys_values, heads, 3)
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], dtype=q.dtype))
    return jnp.einsum("bqd,bkd->bqk", q * scale, k)


@register("_contrib_interleaved_matmul_selfatt_valatt", num_inputs=2,
          aliases=["interleaved_matmul_selfatt_valatt"])
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, *,
                                      heads: int = 1):
    """out = att @ V, back to (L, B, H*D) (reference:
    InterleavedMatMulSelfAttValAtt)."""
    L, B, _ = queries_keys_values.shape
    _, _, v = _split_interleaved(queries_keys_values, heads, 3)
    out = jnp.einsum("bqk,bkd->bqd", attention, v)    # (B*H, L, D)
    D = v.shape[-1]
    return out.reshape(B, heads, L, D).transpose(2, 0, 1, 3).reshape(
        L, B, heads * D)


@register("_contrib_interleaved_matmul_encdec_qk", num_inputs=2,
          aliases=["interleaved_matmul_encdec_qk"])
def interleaved_matmul_encdec_qk(queries, keys_values, *, heads: int = 1):
    Lq, B, HD = queries.shape
    D = HD // heads
    q = queries.reshape(Lq, B, heads, D).transpose(1, 2, 0, 3).reshape(
        B * heads, Lq, D)
    k, _ = _split_interleaved(keys_values, heads, 2)
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, dtype=q.dtype))
    return jnp.einsum("bqd,bkd->bqk", q * scale, k)


@register("_contrib_interleaved_matmul_encdec_valatt", num_inputs=2,
          aliases=["interleaved_matmul_encdec_valatt"])
def interleaved_matmul_encdec_valatt(keys_values, attention, *,
                                     heads: int = 1):
    Lkv, B, _ = keys_values.shape
    _, v = _split_interleaved(keys_values, heads, 2)
    out = jnp.einsum("bqk,bkd->bqd", attention, v)
    D = v.shape[-1]
    Lq = out.shape[1]
    return out.reshape(B, heads, Lq, D).transpose(2, 0, 1, 3).reshape(
        Lq, B, heads * D)


@register("_contrib_AdaptiveAvgPooling2D",
          aliases=["AdaptiveAvgPooling2D"])
def adaptive_avg_pooling2d(data, *, output_size=()):
    """reference: contrib/adaptive_avg_pooling.cc."""
    if not output_size:
        oh = ow = 1
    elif isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = (output_size[0], output_size[-1])
    n, c, h, w = data.shape
    if h % oh == 0 and w % ow == 0:
        x = data.reshape(n, c, oh, h // oh, ow, w // ow)
        return x.mean(axis=(3, 5))
    return jax.image.resize(data, (n, c, oh, ow), method="linear")


@register("_contrib_BilinearResize2D", aliases=["BilinearResize2D"])
def bilinear_resize2d(data, *, height: int = 1, width: int = 1,
                      scale_height=None, scale_width=None,
                      mode: str = "size", align_corners: bool = True):
    """reference: contrib/bilinear_resize.cc.  The reference default is
    align_corners=True (source/dest corners map exactly); jax.image's
    "linear" is half-pixel (align_corners=False), so the True path is an
    explicit gather-lerp."""
    n, c, h, w = data.shape
    if scale_height is not None:
        height = int(h * scale_height)
        width = int(w * scale_width)
    if not align_corners:
        return jax.image.resize(data, (n, c, height, width),
                                method="linear")
    # align-corners mapping degenerates per-axis at size 1 (0/0): that
    # axis samples its center, the other keeps corner alignment
    ys = (jnp.linspace(0.0, h - 1.0, height) if height > 1
          else jnp.full((1,), (h - 1) / 2.0))
    xs = (jnp.linspace(0.0, w - 1.0, width) if width > 1
          else jnp.full((1,), (w - 1) / 2.0))
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0).astype(data.dtype)[None, None, :, None]
    wx = (xs - x0).astype(data.dtype)
    rows = data[:, :, y0, :] * (1 - wy) + data[:, :, y1, :] * wy
    return rows[:, :, :, x0] * (1 - wx) + rows[:, :, :, x1] * wx


@register("_contrib_ROIAlign", num_inputs=2, aliases=["ROIAlign"])
def roi_align(data, rois, *, pooled_size=(), spatial_scale: float = 1.0,
              sample_ratio: int = -1, position_sensitive: bool = False,
              aligned: bool = False):
    """ROIAlign (reference: contrib/roi_align.cc).  Bilinear sampling on a
    regular grid inside each ROI; rois = (R, 5) [batch_idx, x1, y1, x2, y2]."""
    ph, pw = pooled_size
    n, c, h, w = data.shape
    R = rois.shape[0]
    offset = 0.5 if aligned else 0.0
    batch_idx = rois[:, 0].astype(jnp.int32)
    x1 = rois[:, 1] * spatial_scale - offset
    y1 = rois[:, 2] * spatial_scale - offset
    x2 = rois[:, 3] * spatial_scale - offset
    y2 = rois[:, 4] * spatial_scale - offset
    roi_w = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
    roi_h = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
    s = sample_ratio if sample_ratio > 0 else 2
    # sample grid: (R, ph*s, pw*s)
    ys = y1[:, None] + roi_h[:, None] * (
        (jnp.arange(ph * s) + 0.5) / (ph * s))[None, :]
    xs = x1[:, None] + roi_w[:, None] * (
        (jnp.arange(pw * s) + 0.5) / (pw * s))[None, :]

    def bilinear(img, yy, xx):
        y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, w - 1)
        y1_, x1_ = jnp.clip(y0 + 1, 0, h - 1), jnp.clip(x0 + 1, 0, w - 1)
        wy, wx = yy - y0, xx - x0
        v = (img[:, y0[:, None], x0[None, :]] * ((1 - wy)[:, None] * (1 - wx)[None, :])
             + img[:, y0[:, None], x1_[None, :]] * ((1 - wy)[:, None] * wx[None, :])
             + img[:, y1_[:, None], x0[None, :]] * (wy[:, None] * (1 - wx)[None, :])
             + img[:, y1_[:, None], x1_[None, :]] * (wy[:, None] * wx[None, :]))
        return v  # (c, ph*s, pw*s)

    def per_roi(r):
        img = data[batch_idx[r]]
        v = bilinear(img, ys[r], xs[r])
        v = v.reshape(c, ph, s, pw, s).mean(axis=(2, 4))
        return v

    return jax.vmap(per_roi)(jnp.arange(R))


@register("_contrib_index_copy", num_inputs=3, aliases=["index_copy"])
def index_copy(old, index, new):
    return old.at[index.astype(jnp.int32)].set(new)


@register("_contrib_index_array", aliases=["index_array"])
def index_array(data, *, axes=None):
    shape = data.shape
    if axes is None:
        axes = tuple(range(len(shape)))
    else:
        axes = tuple(axes)
    grids = jnp.meshgrid(*[jnp.arange(shape[a]) for a in axes], indexing="ij")
    full = jnp.stack(jnp.meshgrid(
        *[jnp.arange(s) for s in shape], indexing="ij"), axis=-1)
    return full[..., list(axes)].astype(jnp.int64)


@register("_contrib_gelu_erf", aliases=["gelu"])
def gelu_erf(data):
    return jax.nn.gelu(data, approximate=False)


@register("_contrib_gelu_tanh", aliases=["gelu_tanh"])
def gelu_tanh(data):
    return jax.nn.gelu(data, approximate=True)


@register("smooth_l1")
def smooth_l1(data, *, scalar: float = 1.0):
    """reference: tensor/elemwise_binary_scalar_op_extended.cc smooth_l1."""
    s2 = scalar * scalar
    absd = jnp.abs(data)
    return jnp.where(absd < 1.0 / s2, 0.5 * s2 * jnp.square(data),
                     absd - 0.5 / s2)
