"""Pallas TPU kernels: fused flash attention (forward + backward).

The reference's attention kernels (``src/operator/contrib/transformer.cc``,
``_contrib_interleaved_matmul_selfatt_*``) materialize the (L, L) score
matrix — O(L^2) HBM traffic.  This module supplies the TPU-native
replacement (SURVEY.md §5.7 flash/splash mandate): an online-softmax
flash-attention kernel that keeps scores in VMEM tiles, with the standard
FlashAttention-2 backward (recompute P blockwise from the saved
logsumexp).

Design notes:
- grid = (batch*heads, q_blocks, k_blocks), innermost k sequential; the
  running max / denominator / output accumulator live in VMEM scratch and
  carry across k iterations (canonical TPU flash pattern).
- per-row key-length masking (padding masks) rides a scalar-prefetch
  lengths vector; causal masking is an in-kernel iota comparison, and
  fully-masked k blocks are skipped with ``pl.when``.
- matmuls request float32 accumulation (``preferred_element_type``) so
  bf16 inputs hit the MXU without losing the softmax statistics.
- On CPU backends the kernels run in the Pallas interpreter, so the same
  code path is exercised by the virtual-mesh test suite.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAVE_PLTPU = False

from .registry import register

__all__ = ["flash_attention", "pallas_available",
           "ragged_paged_attention", "ragged_paged_attention_reference",
           "ragged_paged_verify", "ragged_paged_verify_reference"]

_NEG_INF = -1e30


def pallas_available() -> bool:
    """True when the Pallas kernels in this module can execute (compiled
    on TPU, interpreted on CPU; both need the pltpu scratch/memory-space
    constructors)."""
    return _HAVE_PLTPU


def _scratch(shape, dtype):
    return pltpu.VMEM(shape, dtype)


def _lens_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _block_mask(s, kv_len, q_start, k_start, causal, block_q, block_k,
                window=-1):
    """Mask a (block_q, block_k) score tile: key padding + causal
    (+ sliding window: key in [q-window+1, q])."""
    k_idx = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = k_idx < kv_len
    if causal:
        q_idx = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = jnp.logical_and(mask, k_idx <= q_idx)
        if window > 0:
            mask = jnp.logical_and(mask, k_idx >= q_idx - (window - 1))
    return jnp.where(mask, s, _NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, causal, block_q,
                block_k, nk, window=-1):
    b = pl.program_id(0)
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    kv_len = lens_ref[b]
    q_start = iq * block_q
    k_start = ik * block_k
    # any work in this block? (causal: block fully above the diagonal;
    # padding: block fully past the key length)
    needed = k_start < kv_len
    if causal:
        needed = jnp.logical_and(needed,
                                 k_start <= q_start + block_q - 1)
        if window > 0:
            needed = jnp.logical_and(
                needed, k_start + block_k - 1 >= q_start - (window - 1))

    @pl.when(needed)
    def _step():
        # q/k/v stay in their storage dtype (bf16 on the training path):
        # bf16xbf16->fp32 is the MXU fast path — upcasting inputs first
        # would halve matmul throughput.  Softmax statistics are fp32.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        s = _block_mask(s, kv_len, q_start, k_start, causal, block_q,
                        block_k, window)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                              # (bq, bk)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(l == 0.0, _NEG_INF,
                               m_scr[:] + jnp.log(safe_l))


# ---------------------------------------------------------------------------
# backward (FlashAttention-2: dQ pass + dK/dV pass, P recomputed)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(lens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_scr, *, sm_scale, causal,
                   block_q, block_k, nk, window=-1):
    b = pl.program_id(0)
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    kv_len = lens_ref[b]
    q_start = iq * block_q
    k_start = ik * block_k
    needed = k_start < kv_len
    if causal:
        needed = jnp.logical_and(needed,
                                 k_start <= q_start + block_q - 1)
        if window > 0:
            needed = jnp.logical_and(
                needed, k_start + block_k - 1 >= q_start - (window - 1))

    @pl.when(needed)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = _block_mask(s, kv_len, q_start, k_start, causal, block_q,
                        block_k, window)
        p = jnp.exp(s - lse_ref[0])                # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(lens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                    sm_scale, causal, block_q, block_k, nq, window=-1):
    b = pl.program_id(0)
    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    kv_len = lens_ref[b]
    q_start = iq * block_q
    k_start = ik * block_k
    needed = k_start < kv_len
    if causal:
        needed = jnp.logical_and(needed,
                                 q_start + block_q - 1 >= k_start)
        if window > 0:
            needed = jnp.logical_and(
                needed, k_start + block_k - 1 >= q_start - (window - 1))

    @pl.when(needed)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        s = _block_mask(s, kv_len, q_start, k_start, causal, block_q,
                        block_k, window)
        p = jnp.exp(s - lse_ref[0])                # (bq, bk)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bk, D)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bq, bk)
        ds = p * (dp - delta_ref[0]) * sm_scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # (bk, D)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing
# ---------------------------------------------------------------------------
def _specs(block_q, block_k, D, Lq, Lk, order):
    """BlockSpecs for (lens, q, k, v[, do, lse, delta]) given grid axis
    order: 'qk' = (b, iq, ik), 'kq' = (b, ik, iq)."""
    if order == "qk":
        qi = lambda b, i, j: (b, i, 0)          # noqa: E731
        ki = lambda b, i, j: (b, j, 0)          # noqa: E731
        rowi = lambda b, i, j: (b, i, 0)        # noqa: E731
    else:
        qi = lambda b, i, j: (b, j, 0)          # noqa: E731
        ki = lambda b, i, j: (b, i, 0)          # noqa: E731
        rowi = lambda b, i, j: (b, j, 0)        # noqa: E731
    q_spec = pl.BlockSpec((1, block_q, D), qi)
    k_spec = pl.BlockSpec((1, block_k, D), ki)
    row_spec = pl.BlockSpec((1, block_q, 1), rowi)
    return q_spec, k_spec, row_spec


def _run(kernel, grid, in_specs, out_shape, out_specs, scratch, inputs,
         interpret):
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_shape=out_shape,
        out_specs=out_specs,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*inputs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash(q, k, v, lens, causal, sm_scale, block_q, block_k, interpret,
           window):
    out, _ = _flash_fwd(q, k, v, lens, causal, sm_scale, block_q,
                        block_k, interpret, window)
    return out


def _flash_fwd(q, k, v, lens, causal, sm_scale, block_q, block_k,
               interpret, window):
    BH, Lq, D = q.shape
    Lk = k.shape[1]
    nq, nk = Lq // block_q, Lk // block_k
    q_spec, k_spec, row_spec = _specs(block_q, block_k, D, Lq, Lk, "qk")
    lens_spec = _lens_spec()
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale,
                               causal=causal, block_q=block_q,
                               block_k=block_k, nk=nk, window=window)
    out, lse = _run(
        kernel, (BH, nq, nk),
        [lens_spec, q_spec, k_spec, k_spec],
        (jax.ShapeDtypeStruct((BH, Lq, D), q.dtype),
         jax.ShapeDtypeStruct((BH, Lq, 1), jnp.float32)),
        (q_spec, row_spec),
        [_scratch((block_q, 1), jnp.float32),
         _scratch((block_q, 1), jnp.float32),
         _scratch((block_q, D), jnp.float32)],
        (lens, q, k, v), interpret)
    return out, (q, k, v, lens, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, window,
               res, dout):
    q, k, v, lens, out, lse = res
    BH, Lq, D = q.shape
    Lk = k.shape[1]
    nq, nk = Lq // block_q, Lk // block_k
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)                  # (BH, Lq, 1)
    lens_spec = _lens_spec()

    q_spec, k_spec, row_spec = _specs(block_q, block_k, D, Lq, Lk, "qk")
    dq = _run(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q,
                          block_k=block_k, nk=nk, window=window),
        (BH, nq, nk),
        [lens_spec, q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        jax.ShapeDtypeStruct((BH, Lq, D), q.dtype),
        q_spec,
        [_scratch((block_q, D), jnp.float32)],
        (lens, q, k, v, dout, lse, delta), interpret)

    q_spec2, k_spec2, row_spec2 = _specs(block_q, block_k, D, Lq, Lk,
                                         "kq")
    dk, dv = _run(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                          causal=causal, block_q=block_q,
                          block_k=block_k, nq=nq, window=window),
        (BH, nk, nq),
        [lens_spec, q_spec2, k_spec2, k_spec2, q_spec2, row_spec2,
         row_spec2],
        (jax.ShapeDtypeStruct((BH, Lk, D), k.dtype),
         jax.ShapeDtypeStruct((BH, Lk, D), v.dtype)),
        (k_spec2, k_spec2),
        [_scratch((block_k, D), jnp.float32),
         _scratch((block_k, D), jnp.float32)],
        (lens, q, k, v, dout, lse, delta), interpret)
    dlens = np.zeros(lens.shape, jax.dtypes.float0)
    return dq, dk, dv, dlens


_flash.defvjp(_flash_fwd, _flash_bwd)


def _ceil_to(x, m):
    return (x + m - 1) // m * m


def _default_blocks(Lq, Lk, D):
    """Block sizes per (seqlen, head-dim), tuned on a v5e chip (see
    benchmark/opperf.py flash rows).  Bigger k blocks amortize the
    per-block softmax bookkeeping; VMEM comfortably holds a
    (256, 512) fp32 score tile at D<=128.  Override with
    MXNET_FLASH_BLOCK_Q/MXNET_FLASH_BLOCK_K or the explicit args."""
    from ..base import get_env
    bq = get_env("MXNET_FLASH_BLOCK_Q", None)
    bk = get_env("MXNET_FLASH_BLOCK_K", None)
    if bq or bk:
        return int(bq or 128), int(bk or 128)
    if Lk <= 128:
        return 128, 128
    if Lk <= 1024:
        return min(512, _ceil_to(Lq, 8)), min(512, _ceil_to(Lk, 8))
    return min(1024, _ceil_to(Lq, 8)), min(1024, _ceil_to(Lk, 8))


def flash_attention(q, k, v, lengths=None, causal=False, sm_scale=None,
                    block_q=None, block_k=None, interpret=None,
                    window=None):
    """Fused attention over (B*H, L, D) tensors.

    ``lengths``: optional int32 (B*H,) valid key lengths (padding mask).
    ``window``: optional causal sliding-window width — query q attends
    keys in [q-window+1, q] (Mistral/Longformer-style local attention);
    out-of-window blocks are SKIPPED, so compute scales O(L*window)
    (the splash-style sparsity SURVEY §5.7 asks for).  Requires
    causal=True.  Returns (B*H, Lq, D) in the query dtype.  Block sizes
    default to a per-(seqlen, head-dim) tuned table (_default_blocks).
    """
    if not pallas_available():
        from ..base import MXNetError
        raise MXNetError(
            "flash_attention requires jax.experimental.pallas.tpu "
            "(check mx.runtime.Features()['PALLAS'])")
    BH, Lq, D = q.shape
    Lk = k.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    dbq, dbk = _default_blocks(Lq, Lk, D)
    block_q = block_q or dbq
    block_k = block_k or dbk
    block_q = min(block_q, _ceil_to(Lq, 8))
    block_k = min(block_k, _ceil_to(Lk, 8))
    Lq_p, Lk_p = _ceil_to(Lq, block_q), _ceil_to(Lk, block_k)
    if lengths is None:
        lengths = jnp.full((BH,), Lk, jnp.int32)
    else:
        lengths = lengths.astype(jnp.int32)
    if Lq_p != Lq:
        q = jnp.pad(q, ((0, 0), (0, Lq_p - Lq), (0, 0)))
    if Lk_p != Lk:
        k = jnp.pad(k, ((0, 0), (0, Lk_p - Lk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, Lk_p - Lk), (0, 0)))
    if window is not None:
        from ..base import MXNetError
        if not causal:
            raise MXNetError(
                "flash_attention: window requires causal=True")
        if int(window) < 1:
            raise MXNetError(
                f"flash_attention: window must be >= 1, got {window}")
    out = _flash(q, k, v, lengths, causal, float(sm_scale), block_q,
                 block_k, bool(interpret),
                 -1 if window is None else int(window))
    return out[:, :Lq] if Lq_p != Lq else out


# ---------------------------------------------------------------------------
# op-registry frontends (layout contract of the interleaved MHA ops:
# qkv (L, B, H*3*D) -> out (L, B, H*D); reference transformer.cc)
# ---------------------------------------------------------------------------
@register("_contrib_flash_selfatt", num_inputs=2,
          aliases=["flash_selfatt"])
def flash_selfatt(queries_keys_values, valid_length, *, heads: int = 1,
                  causal: bool = False, window: int = -1):
    """Flash-attention drop-in for the interleaved selfatt qk->softmax->
    valatt chain.  ``valid_length``: (B,) float/int valid KEY lengths.
    ``window > 0``: causal sliding-window attention of that width.
    """
    L, B, H3D = queries_keys_values.shape
    D = H3D // (heads * 3)
    x = queries_keys_values.reshape(L, B, heads, 3, D)
    # (L, B, H, D) -> (B*H, L, D)
    q, k, v = (x[:, :, :, i, :].transpose(1, 2, 0, 3)
               .reshape(B * heads, L, D) for i in range(3))
    lens = jnp.repeat(valid_length.astype(jnp.int32), heads)
    out = flash_attention(q, k, v, lengths=lens, causal=causal,
                          window=None if window <= 0 else window)
    return out.reshape(B, heads, L, D).transpose(2, 0, 1, 3).reshape(
        L, B, heads * D)


@register("_contrib_flash_selfatt_nomask", num_inputs=1,
          aliases=["flash_selfatt_nomask"])
def flash_selfatt_nomask(queries_keys_values, *, heads: int = 1,
                         causal: bool = False, window: int = -1):
    """flash_selfatt without a padding mask (full key length)."""
    L, B, H3D = queries_keys_values.shape
    D = H3D // (heads * 3)
    x = queries_keys_values.reshape(L, B, heads, 3, D)
    q, k, v = (x[:, :, :, i, :].transpose(1, 2, 0, 3)
               .reshape(B * heads, L, D) for i in range(3))
    out = flash_attention(q, k, v, causal=causal,
                          window=None if window <= 0 else window)
    return out.reshape(B, heads, L, D).transpose(2, 0, 1, 3).reshape(
        L, B, heads * D)


# ---------------------------------------------------------------------------
# ragged paged attention (LLM decode: one query token per sequence, K/V
# read through per-sequence block tables out of a fixed-page pool —
# "Ragged Paged Attention" kernel design, PAPERS.md)
# ---------------------------------------------------------------------------
def _paged_fwd_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                      m_scr, l_scr, acc_scr, *, sm_scale, page_size,
                      n_pages):
    """One (sequence, head, page) grid step of decode attention.

    The page axis is innermost and sequential, so the online-softmax
    statistics (m/l/acc scratch) carry across the pages of one
    (sequence, head) exactly like the flash kernel's k axis.  Which
    physical page backs grid step (b, h, p) is decided by the BlockSpec
    index map reading the scalar-prefetched block table — the kernel
    body never sees a page id, only its (page_size, D) tile.
    """
    b = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    ctx = len_ref[b]
    start = p * page_size

    # skip pages entirely past the sequence's context (and everything
    # for an inactive slot, ctx == 0: output falls out as zeros)
    @pl.when(start < ctx)
    def _step():
        q = q_ref[0]                            # (1, D)
        k = k_ref[0, :, 0]                      # (page_size, D)
        v = v_ref[0, :, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (1, ps)
        idx = start + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        s = jnp.where(idx < ctx, s, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p_ = jnp.exp(s - m_new)                 # (1, ps)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p_, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p_.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(p == n_pages - 1)
    def _finish():
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def ragged_paged_attention(q, k_pages, v_pages, block_tables,
                           context_lens, sm_scale=None, interpret=None):
    """Decode attention over a paged KV cache (Pallas TPU kernel).

    - ``q``: (B, H, D) — ONE query token per sequence slot (the ragged
      decode batch; inactive slots carry ``context_lens == 0``).
    - ``k_pages`` / ``v_pages``: (num_pages, page_size, H, D) — the
      preallocated device pool (``serving.kv_cache``).
    - ``block_tables``: (B, pages_per_seq) int32 — physical page of each
      logical page of each sequence; entries past the sequence's length
      must point at a valid (e.g. the null) page.
    - ``context_lens``: (B,) int32 — tokens of valid context per slot,
      INCLUDING the token whose K/V was just written; 0 = inactive slot
      (output row is zeros).

    The grid is (B, H, pages_per_seq) with pages innermost-sequential;
    the block table rides scalar prefetch so the page indirection is an
    index-map lookup, not in-kernel pointer math.  Returns (B, H, D) in
    the query dtype.  Pure-jax twin:
    :func:`ragged_paged_attention_reference` (CPU fallback + test
    oracle).
    """
    if not pallas_available():
        from ..base import MXNetError
        raise MXNetError(
            "ragged_paged_attention requires jax.experimental.pallas.tpu "
            "(check mx.runtime.Features()['PALLAS']); use "
            "ragged_paged_attention_reference on other backends")
    B, H, D = q.shape
    n_pool, page_size, HK, DK = k_pages.shape
    if (HK, DK) != (H, D) or v_pages.shape != k_pages.shape:
        from ..base import MXNetError
        raise MXNetError(
            f"ragged_paged_attention: q (B,H,D)={q.shape} inconsistent "
            f"with k_pages {k_pages.shape} / v_pages {v_pages.shape} "
            f"(want (num_pages, page_size, {H}, {D}))")
    n_pages = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    block_tables = block_tables.astype(jnp.int32)
    context_lens = context_lens.astype(jnp.int32)

    q_spec = pl.BlockSpec((1, 1, D), lambda b, h, p, bt, ln: (b, h, 0))
    kv_spec = pl.BlockSpec(
        (1, page_size, 1, D),
        lambda b, h, p, bt, ln: (bt[b, p], 0, h, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, n_pages),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        scratch_shapes=[_scratch((1, 1), jnp.float32),
                        _scratch((1, 1), jnp.float32),
                        _scratch((1, D), jnp.float32)],
    )
    kernel = functools.partial(_paged_fwd_kernel,
                               sm_scale=float(sm_scale),
                               page_size=page_size, n_pages=n_pages)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=bool(interpret),
    )(block_tables, context_lens, q, k_pages, v_pages)


def ragged_paged_attention_reference(q, k_pages, v_pages, block_tables,
                                     context_lens, sm_scale=None):
    """Pure-jax twin of :func:`ragged_paged_attention` — same signature
    and semantics (inactive ``context_lens == 0`` slots yield zeros),
    used as the CPU serving path and the kernel-parity oracle.  Gathers
    each sequence's pages into a contiguous (pages*page_size) context
    and runs masked softmax attention."""
    B, H, D = q.shape
    page_size = k_pages.shape[1]
    n_pages = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    block_tables = block_tables.astype(jnp.int32)
    context_lens = context_lens.astype(jnp.int32)
    # (B, n_pages, page_size, H, D) -> (B, T, H, D), T = n_pages * ps
    k = k_pages[block_tables].reshape(B, n_pages * page_size, H, D)
    v = v_pages[block_tables].reshape(B, n_pages * page_size, H, D)
    s = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    valid = (jnp.arange(n_pages * page_size)[None, :]
             < context_lens[:, None])                       # (B, T)
    s = jnp.where(valid[:, None, :], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m) * valid[:, None, :]
    l = jnp.sum(e, axis=-1, keepdims=True)                  # (B, H, 1)
    out = jnp.einsum("bht,bthd->bhd", e, v.astype(jnp.float32))
    return (out / jnp.where(l == 0.0, 1.0, l)).astype(q.dtype)


# ---------------------------------------------------------------------------
# ragged paged verify (multi-token window over a paged context: the
# speculative-decoding verification shape — k+1 query tokens per
# sequence, each attending causally over the full paged prefix — and
# the tail prefill of a prefix-cache hit; docs/serving.md §9)
# ---------------------------------------------------------------------------
def _paged_verify_kernel(bt_ref, start_ref, len_ref, q_ref, k_ref,
                         v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                         sm_scale, page_size, n_pages, width):
    """One (sequence, head, page) grid step of windowed verify
    attention.  Identical page-innermost online-softmax structure to
    :func:`_paged_fwd_kernel`, but the query block is the whole (W, D)
    window and the causal mask is per ROW: window row ``w`` (global
    position ``start + w``) sees key ``j`` iff ``j <= start + w``.
    Page 0 always holds valid keys for every valid row (all rows attend
    from position 0), so a valid row's softmax statistics are finite
    from its first processed block; rows past ``length`` accumulate
    garbage the wrapper zeroes."""
    b = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    start = start_ref[b]
    n_valid = len_ref[b]
    page_start = p * page_size

    # skip pages entirely past the last valid row's causal horizon
    # (start + n_valid - 1); an inactive slot (n_valid == 0) skips all
    @pl.when(page_start < start + n_valid)
    def _step():
        q = q_ref[0, :, 0]                      # (W, D)
        k = k_ref[0, :, 0]                      # (page_size, D)
        v = v_ref[0, :, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (W, ps)
        idx = page_start + jax.lax.broadcasted_iota(
            jnp.int32, (width, page_size), 1)
        row = jax.lax.broadcasted_iota(
            jnp.int32, (width, page_size), 0)
        mask = jnp.logical_and(idx <= start + row, row < n_valid)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p_ = jnp.exp(s - m_new)                 # (W, ps)
        l_scr[:] = l_scr[:] * corr + jnp.sum(p_, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p_.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(p == n_pages - 1)
    def _finish():
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, :, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def ragged_paged_verify(q, k_pages, v_pages, block_tables, starts,
                        lengths, sm_scale=None, interpret=None):
    """Multi-token verify attention over a paged KV cache (Pallas TPU
    kernel).

    - ``q``: (B, W, H, D) — a W-token window per sequence slot (the
      speculative k+1 verification window, or a prefix-cache tail).
    - ``k_pages`` / ``v_pages``: (num_pages, page_size, H, D) pool.
    - ``block_tables``: (B, pages_per_seq) int32 — as in
      :func:`ragged_paged_attention`.
    - ``starts``: (B,) int32 — global position of each slot's window
      row 0; K/V of positions below it are read from the cache pages,
      and the window's own K/V must already be written THROUGH the same
      block table (the verify forward writes before it attends).
    - ``lengths``: (B,) int32 — valid rows per window (0 = inactive
      slot).  Rows past ``lengths`` come back as zeros.

    Window row ``w`` attends causally over positions
    ``0 .. starts[b] + w`` — exactly prefill semantics when
    ``starts == 0`` and decode semantics when ``W == 1``.  Returns
    (B, W, H, D) in the query dtype; pure-jax twin:
    :func:`ragged_paged_verify_reference`.
    """
    if not pallas_available():
        from ..base import MXNetError
        raise MXNetError(
            "ragged_paged_verify requires jax.experimental.pallas.tpu "
            "(check mx.runtime.Features()['PALLAS']); use "
            "ragged_paged_verify_reference on other backends")
    B, W, H, D = q.shape
    n_pool, page_size, HK, DK = k_pages.shape
    if (HK, DK) != (H, D) or v_pages.shape != k_pages.shape:
        from ..base import MXNetError
        raise MXNetError(
            f"ragged_paged_verify: q (B,W,H,D)={q.shape} inconsistent "
            f"with k_pages {k_pages.shape} / v_pages {v_pages.shape} "
            f"(want (num_pages, page_size, {H}, {D}))")
    n_pages = block_tables.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    block_tables = block_tables.astype(jnp.int32)
    starts = starts.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)

    q_spec = pl.BlockSpec((1, W, 1, D),
                          lambda b, h, p, bt, st, ln: (b, 0, h, 0))
    kv_spec = pl.BlockSpec(
        (1, page_size, 1, D),
        lambda b, h, p, bt, st, ln: (bt[b, p], 0, h, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, H, n_pages),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=q_spec,
        scratch_shapes=[_scratch((W, 1), jnp.float32),
                        _scratch((W, 1), jnp.float32),
                        _scratch((W, D), jnp.float32)],
    )
    kernel = functools.partial(_paged_verify_kernel,
                               sm_scale=float(sm_scale),
                               page_size=page_size, n_pages=n_pages,
                               width=W)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, W, H, D), q.dtype),
        interpret=bool(interpret),
    )(block_tables, starts, lengths, q, k_pages, v_pages)
    # defined semantics for padded rows (they accumulate garbage in the
    # kernel — their every score is masked, so the online max never
    # leaves the -inf floor and exp(s - m) degenerates to 1)
    valid = jnp.arange(W)[None, :] < lengths[:, None]       # (B, W)
    return jnp.where(valid[:, :, None, None], out,
                     jnp.zeros((), out.dtype))


def ragged_paged_verify_reference(q, k_pages, v_pages, block_tables,
                                  starts, lengths, sm_scale=None):
    """Pure-jax twin of :func:`ragged_paged_verify` — same signature
    and semantics (rows past ``lengths`` yield zeros), used as the CPU
    serving path and the kernel-parity oracle."""
    B, W, H, D = q.shape
    page_size = k_pages.shape[1]
    n_pages = block_tables.shape[1]
    T = n_pages * page_size
    if sm_scale is None:
        sm_scale = 1.0 / float(np.sqrt(D))
    block_tables = block_tables.astype(jnp.int32)
    starts = starts.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)
    k = k_pages[block_tables].reshape(B, T, H, D)
    v = v_pages[block_tables].reshape(B, T, H, D)
    s = jnp.einsum("bwhd,bthd->bhwt", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    row_pos = starts[:, None] + jnp.arange(W)[None, :]      # (B, W)
    mask = (jnp.arange(T)[None, None, :] <= row_pos[:, :, None]) \
        & (jnp.arange(W)[None, :, None] < lengths[:, None, None])
    s = jnp.where(mask[:, None], s, _NEG_INF)               # (B,H,W,T)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m) * mask[:, None]
    l = jnp.sum(e, axis=-1)                                 # (B, H, W)
    out = jnp.einsum("bhwt,bthd->bwhd", e, v.astype(jnp.float32))
    denom = jnp.where(l == 0.0, 1.0, l).transpose(0, 2, 1)  # (B, W, H)
    return (out / denom[:, :, :, None]).astype(q.dtype)


@register("_contrib_ragged_paged_attention", num_inputs=5,
          differentiable=False, aliases=["ragged_paged_attention_op"])
def ragged_paged_attention_auto(q, k_pages, v_pages, block_tables,
                                context_lens):
    """Registry frontend for decode-time paged attention: the Pallas
    kernel on TPU backends, the pure-jax reference elsewhere (the same
    dispatch the serving decode engine uses).  Block tables and context
    lengths accept any numeric dtype (cast to int32)."""
    bt = block_tables.astype(jnp.int32)
    lens = context_lens.astype(jnp.int32)
    if pallas_available() and jax.default_backend() == "tpu":
        return ragged_paged_attention(q, k_pages, v_pages, bt, lens)
    return ragged_paged_attention_reference(q, k_pages, v_pages, bt, lens)
